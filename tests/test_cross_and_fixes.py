"""Two-level allreduce, stall inspector, enqueue validation, jit-safe
compression — regression tests for review findings."""
import logging
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_two_level_allreduce_matches_flat(hvd):
    from horovod_tpu.core.mesh import build_hierarchical_mesh
    from horovod_tpu.ops.cross import two_level_allreduce
    mesh = build_hierarchical_mesh(jax.devices(), local_size=4)  # (2, 4)
    assert mesh.devices.shape == (2, 4)
    x = np.random.RandomState(0).randn(8, 37).astype(np.float32)  # odd size
    out = np.asarray(two_level_allreduce(jnp.asarray(x), hvd.Sum, mesh))
    np.testing.assert_allclose(out, np.tile(x.sum(0), (8, 1)), rtol=1e-4)
    avg = np.asarray(two_level_allreduce(jnp.asarray(x), hvd.Average, mesh))
    np.testing.assert_allclose(avg, np.tile(x.mean(0), (8, 1)), rtol=1e-4)


def test_hierarchical_env_flag():
    import horovod_tpu as hvd
    os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    os.environ["HOROVOD_LOCAL_SIZE"] = "4"
    try:
        hvd.shutdown()
        hvd.init()
        x = np.random.RandomState(1).randn(8, 16).astype(np.float32)
        out = np.asarray(hvd.allreduce(x, hvd.Sum))
        np.testing.assert_allclose(out, np.tile(x.sum(0), (8, 1)), rtol=1e-4)
    finally:
        del os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"]
        del os.environ["HOROVOD_LOCAL_SIZE"]
        hvd.shutdown()


def test_async_enqueue_validates_shape(hvd):
    with pytest.raises(ValueError, match="stacked"):
        hvd.allreduce_async(np.ones((16, 4), np.float32), hvd.Sum,
                            name="badshape")
    # a tensor whose size is divisible by n but with wrong leading axis must
    # NOT slip through the fused reshape path
    with pytest.raises(ValueError, match="stacked"):
        hvd.allgather_async(np.ones((4, 2), np.float32), name="badshape2")


def test_stall_inspector_warns(caplog):
    import horovod_tpu as hvd
    os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "0.5"
    try:
        hvd.shutdown()
        hvd.init()
        eng = hvd.core.basics.get_engine()
        # simulate a stuck collective: register an outstanding name directly
        # (a real hang would come from a wedged device queue)
        with eng._qlock:
            eng._outstanding["stuck.tensor"] = time.monotonic() - 10.0
        with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
            time.sleep(1.0)
        assert any("stuck.tensor" in r.message for r in caplog.records)
    finally:
        del os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"]
        hvd.shutdown()


def test_spar_compressor_jit_safe(hvd):
    from horovod_tpu.optim.compression import SparCompressor

    @jax.jit
    def f(x):
        c, _ = SparCompressor.compress(x)
        return c

    x = jnp.ones((64,))
    a = f(x)
    b = f(x * 2.0)   # second call under jit must not raise tracer errors
    assert a.shape == x.shape and b.shape == x.shape
    # value-dependent keys: different inputs give different masks (w.h.p.)
    assert not np.array_equal(np.asarray(a) != 0, np.asarray(b) != 0)


def test_disable_group_fusion_env():
    import horovod_tpu as hvd
    os.environ["HOROVOD_DISABLE_GROUP_FUSION"] = "1"
    try:
        hvd.shutdown()
        hvd.init()
        eng = hvd.core.basics.get_engine()
        before = eng.tensors_fused
        hs = [hvd.allreduce_async(np.ones((8, 4), np.float32), hvd.Sum,
                                  name=f"nf.{i}") for i in range(6)]
        for h in hs:
            h.wait()
        assert eng.tensors_fused == before  # nothing fused
    finally:
        del os.environ["HOROVOD_DISABLE_GROUP_FUSION"]
        hvd.shutdown()
