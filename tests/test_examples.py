"""Smoke-run the examples suite (the reference CI runs its examples as
integration tests; .buildkite pipeline). Each runs as a subprocess on a
virtual 8-device CPU mesh."""
import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _run(script, *args, timeout=240):
    env = dict(os.environ)
    env["HVD_EXAMPLE_CPU"] = "8"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES)
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=EXAMPLES)
    assert r.returncode == 0, f"{script} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


@pytest.mark.parametrize("script,args,expect", [
    ("synthetic_benchmark.py", ["--model", "resnet18", "--num-iters", "2",
                                "--num-warmup", "1"], "Total img/sec"),
    ("mnist_train.py", ["--epochs", "1", "--batch-size", "8"], "epoch 0"),
    ("gpt_hybrid_parallel.py", ["--steps", "1", "--seq-len", "64"],
     "loss="),
    ("elastic_train.py", [], "epoch 2 done"),
    ("elastic_hybrid.py", [], "misfit world rejected"),
    ("adasum_example.py", [], "Adasum"),
    ("process_sets_example.py", [], "even-set sum"),
    ("data_service_example.py", [], "served batches"),
    ("vit_train.py", ["--epochs", "1", "--batch-size", "16"], "loss="),
    ("moe_expert_parallel.py", ["--steps", "2"], "experts sharded 4-way"),
    ("haiku_train.py", [], "haiku accuracy="),
    ("checkpoint_resume.py", [], "resumed from step 2"),
    ("compression_fusion_sweep.py", ["--steps", "2"], "sweep done"),
    ("join_uneven_data.py", [], "last joined rank = 7"),
    ("llama_pretrain.py", ["--steps", "2"], "gqa 4q/2kv"),
    ("llama_pretrain.py", ["--steps", "2", "--attention", "zigzag"],
     "loss"),
    ("pp_pipeline.py", ["--steps", "3"], "GPipe: 4 stages"),
    ("pp_pipeline.py", ["--steps", "2", "--schedule", "1f1b"],
     "1F1B schedule"),
    ("pp_pipeline.py", ["--steps", "2", "--model", "gpt", "--stages",
                        "2", "--virtual", "2", "--microbatches", "2"],
     "gpt pipeline done"),
    ("lightning_estimator.py", [], "lightning val_loss"),
])
def test_example_runs(script, args, expect):
    out = _run(script, *args)
    assert expect in out, f"{script} output missing {expect!r}:\n{out}"


def test_keras_example_under_hvdrun():
    """The keras front end end-to-end: hvdrun -np 2 over the shm plane."""
    pytest.importorskip("tensorflow")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES) + os.pathsep + \
        env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, os.path.join(EXAMPLES, "keras_train.py")],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=EXAMPLES)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "final averaged accuracy" in r.stdout


def test_torch_ddp_example_single_process():
    env = dict(os.environ)
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE"):
        env.pop(k, None)
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES)
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "torch_cpu_ddp.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=EXAMPLES)
    assert r.returncode == 0, r.stderr
    assert "mean loss" in r.stdout


def test_tf2_custom_loop_example_under_hvdrun():
    """The TF2-eager front end end-to-end: hvdrun -np 2."""
    pytest.importorskip("tensorflow")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES) + os.pathsep + \
        env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, os.path.join(EXAMPLES, "tf2_custom_loop.py")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=EXAMPLES)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "replicas identical across 2 rank(s)" in r.stdout


def test_ray_executor_example_local_backend():
    env = dict(os.environ)
    for k in ("HOROVOD_RANK", "HOROVOD_SIZE"):
        env.pop(k, None)
    env["PYTHONPATH"] = os.path.dirname(EXAMPLES)
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, "ray_executor.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=EXAMPLES)
    assert r.returncode == 0, r.stderr
    assert "2 workers" in r.stdout and "driver-side probe ok" in r.stdout
