"""Aux subsystem tests: autotune, callbacks, SyncBatchNorm, data loaders,
timeline."""
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


class TestBayesOpt:
    def test_gp_fits_and_predicts(self):
        from horovod_tpu.autotune.bayes import GaussianProcess
        gp = GaussianProcess(length_scale=0.5)
        x = np.linspace(0, 1, 8)[:, None]
        y = np.sin(3 * x[:, 0])
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        np.testing.assert_allclose(mu, y, atol=0.05)
        assert (sigma < 0.2).all()

    def test_optimizer_finds_peak(self):
        from horovod_tpu.autotune.bayes import BayesianOptimizer
        opt = BayesianOptimizer([(0.0, 10.0)], seed=1)
        f = lambda x: -(x - 7.0) ** 2
        for _ in range(25):
            x = opt.suggest()
            opt.tell(x, f(x[0]))
        best_x, _ = opt.best()
        assert abs(best_x[0] - 7.0) < 1.5

    def test_parameter_manager_converges_and_logs(self, tmp_path):
        from horovod_tpu.autotune.tuner import ParameterManager
        log = tmp_path / "autotune.csv"
        pm = ParameterManager(warmup_samples=1, steps_per_sample=2,
                              max_samples=5, log_path=str(log))
        # feed synthetic traffic until it pins a best config
        for _ in range(100):
            if not pm.active:
                break
            pm.record(1 << 20)
        assert not pm.active
        content = log.read_text()
        assert "fusion_mb" in content and ",1\n" in content

    def test_engine_autotune_integration(self):
        import horovod_tpu as hvd
        os.environ["HOROVOD_AUTOTUNE"] = "1"
        try:
            hvd.shutdown()
            hvd.init()
            eng = hvd.core.basics.get_engine()
            assert eng.tuner is not None
            for i in range(30):
                hs = [hvd.allreduce_async(
                    np.ones((8, 64), np.float32), hvd.Sum,
                    name=f"at.{i}.{j}") for j in range(3)]
                for h in hs:
                    h.wait()
            assert eng.tuner.samples_taken > 0
        finally:
            del os.environ["HOROVOD_AUTOTUNE"]
            hvd.shutdown()


class TestCallbacks:
    def test_lr_warmup_ramps_to_size_times_lr(self, hvd):
        from horovod_tpu.callbacks import (LearningRate,
                                           LearningRateWarmupCallback)
        lr = LearningRate(0.1)
        cb = LearningRateWarmupCallback(lr, warmup_epochs=2,
                                        steps_per_epoch=10)
        cb.on_batch_begin(0, epoch=0)
        start = lr.value
        cb.on_batch_begin(9, epoch=1)
        near_end = lr.value
        cb.on_batch_begin(0, epoch=2)
        assert start < near_end < lr.value
        np.testing.assert_allclose(lr.value, 0.1 * 8)

    def test_lr_schedule_staircase(self, hvd):
        from horovod_tpu.callbacks import (LearningRate,
                                           LearningRateScheduleCallback)
        lr = LearningRate(0.1)
        cb = LearningRateScheduleCallback(lr, multiplier=0.1, start_epoch=2)
        cb.on_epoch_begin(0)
        v0 = lr.value
        cb.on_epoch_begin(3)
        np.testing.assert_allclose(lr.value, 0.1 * 8 * 0.1)
        assert lr.value != v0

    def test_metric_average(self, hvd):
        from horovod_tpu.callbacks import MetricAverageCallback
        cb = MetricAverageCallback()
        logs = {"loss": np.arange(8, dtype=np.float32)}
        cb.on_epoch_end(0, logs)
        np.testing.assert_allclose(logs["loss"], 3.5)

    def test_broadcast_callback(self, hvd):
        from horovod_tpu.callbacks import BroadcastGlobalVariablesCallback
        state = {"w": np.random.RandomState(0).randn(8, 3).astype(np.float32)}
        holder = {}
        cb = BroadcastGlobalVariablesCallback(
            lambda: state, lambda s: holder.update(s), root_rank=2)
        cb.on_train_begin()
        np.testing.assert_array_equal(np.asarray(holder["w"]),
                                      np.tile(state["w"][2], (8, 1)))


class TestSyncBatchNorm:
    def test_stats_span_devices(self, hvd):
        from horovod_tpu.optim.sync_batch_norm import SyncBatchNorm
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()), ("hvd",))
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32) * 3 + 1

        bn = SyncBatchNorm(axis_name="hvd", use_running_average=False)
        variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((2, 4)))

        def blk(xs):
            y, _ = bn.apply(variables, xs, mutable=["batch_stats"])
            return y

        f = jax.jit(jax.shard_map(blk, mesh=mesh, in_specs=P("hvd"),
                                  out_specs=P("hvd")))
        out = np.asarray(f(x))
        # global normalization: overall mean ~0, var ~1
        np.testing.assert_allclose(out.mean(0), 0.0, atol=1e-4)
        np.testing.assert_allclose(out.std(0), 1.0, atol=1e-2)

    def test_local_bn_differs(self, hvd):
        # sanity: per-device stats would NOT normalize globally when device
        # blocks have different distributions
        from horovod_tpu.optim.sync_batch_norm import SyncBatchNorm
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()), ("hvd",))
        x = np.concatenate([np.full((8, 2), i, np.float32)
                            for i in range(8)])  # block i constant i
        bn = SyncBatchNorm(axis_name="hvd")
        variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))

        def blk(xs):
            y, _ = bn.apply(variables, xs, mutable=["batch_stats"])
            return y

        f = jax.jit(jax.shard_map(blk, mesh=mesh, in_specs=P("hvd"),
                                  out_specs=P("hvd")))
        out = np.asarray(f(x))
        # with global stats, block values normalize to distinct z-scores
        assert len(np.unique(out.round(3)[:, 0])) == 8


class TestDataLoader:
    def test_async_prefetch_order(self):
        from horovod_tpu.data.loader import (AsyncDataLoaderMixin,
                                             BaseDataLoader)

        class Loader(BaseDataLoader):
            def __len__(self):
                return 10

            def _iterate(self):
                yield from range(10)

        class AsyncLoader(AsyncDataLoaderMixin, Loader):
            pass

        loader = AsyncLoader(async_loader_queue_size=3)
        assert list(loader) == list(range(10))
        assert list(loader) == list(range(10))  # reusable
        loader.close_async_loader()

    def test_shard_indices(self):
        from horovod_tpu.data.loader import shard_indices
        shards = [shard_indices(10, r, 4) for r in range(4)]
        # padded to 12, every rank 3 samples
        assert all(len(s) == 3 for s in shards)
        covered = set().union(*[set(s) for s in shards])
        assert covered == set(range(10))

    def test_shard_indices_drop_remainder(self):
        from horovod_tpu.data.loader import shard_indices
        shards = [shard_indices(10, r, 4, drop_remainder=True)
                  for r in range(4)]
        assert all(len(s) == 2 for s in shards)


class TestTimeline:
    def test_timeline_events_roundtrip(self, hvd, tmp_path):
        path = tmp_path / "tl.json"
        hvd.start_timeline(str(path), mark_cycles=True)
        h = hvd.allreduce_async(np.ones((8, 4), np.float32), name="tl.t")
        h.wait()
        hvd.stop_timeline()
        data = json.load(open(path))
        names = [e["name"] for e in data["traceEvents"]]
        assert "QUEUED" in names

    def test_double_start_rejected(self, hvd, tmp_path):
        hvd.start_timeline(str(tmp_path / "a.json"))
        with pytest.raises(ValueError):
            hvd.start_timeline(str(tmp_path / "b.json"))
        hvd.stop_timeline()


def test_stacked_rank_helper(hvd):
    """Per-device rank values for stacked computations (the doc'd port
    path for scripts using per-rank hvd.rank() semantics)."""
    r = hvd.stacked_rank()
    assert r.tolist() == list(range(hvd.size()))
    # canonical use: per-rank contribution derived from the rank index
    x = (r[:, None] * np.ones((hvd.size(), 2), np.float32))
    out = np.asarray(hvd.allreduce(x, hvd.Sum))
    expect = sum(range(hvd.size()))
    np.testing.assert_allclose(out, np.full((hvd.size(), 2), expect))


def test_profiler_range_disable_env(monkeypatch):
    from horovod_tpu.ops import collective_ops as co
    co._profiler_disabled = None
    monkeypatch.setenv("HOROVOD_DISABLE_NVTX_RANGES", "1")
    rng = co.profiler_range("x")
    assert rng is co._NULL_RANGE
    with rng:
        pass
    with rng:                      # nullcontext is reusable
        pass
    co._profiler_disabled = None
    monkeypatch.delenv("HOROVOD_DISABLE_NVTX_RANGES")
    import jax
    assert isinstance(co.profiler_range("y"), jax.profiler.TraceAnnotation)
    co._profiler_disabled = None


def test_autotune_end_to_end_pins_knobs(tmp_path, monkeypatch):
    """End-to-end tuning claim (VERDICT weak #8): with HOROVOD_AUTOTUNE=1
    the ENGINE (not just the GP in isolation) samples knob settings over
    real allreduce traffic, logs scores, and pins a best configuration —
    the reference's warmup-sample-pin lifecycle (parameter_manager.h:33)."""
    log = tmp_path / "tune.csv"
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_LOG", str(log))
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        eng = hvd_mod.core.basics.get_engine()
        tuner = eng.tuner
        assert tuner is not None and tuner.active
        tuner.max_samples = 3                 # keep the loop short
        n = hvd_mod.size()
        x = np.ones((n, 128), np.float32)
        step = 0
        # drive engine cycles until the tuner pins (bounded)
        while tuner.active and step < 400:
            hvd_mod.synchronize(
                hvd_mod.allreduce_async(x, hvd_mod.Sum,
                                        name=f"tune_{step}"))
            step += 1
        assert not tuner.active, "tuner never pinned a configuration"
        # pinned values were adopted by the engine (poll: active flips on
        # the engine thread a moment before the engine copies the knobs)
        import time
        deadline = time.monotonic() + 5.0
        while eng.fusion_threshold != tuner.fusion_threshold_bytes and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.fusion_threshold == tuner.fusion_threshold_bytes
        # categorical knob propagated to the live config (collective_ops
        # re-reads it per call)
        assert hvd_mod.core.basics.get_config().hierarchical_allreduce \
            == tuner.two_level_allreduce
        assert hvd_mod.core.basics.get_config().compression \
            == tuner.compression_wire
        # CSV log recorded sampled + final scores
        lines = log.read_text().strip().splitlines()
        assert lines[0] == ("fusion_mb,cycle_ms,two_level,compression,"
                            "algo_small,algo_large,bytes_per_sec,final")
        assert any(ln.endswith(",1") for ln in lines[1:]), lines
    finally:
        hvd_mod.shutdown()


class TestSlopeTiming:
    def test_slope_cancels_fixed_latency(self):
        import sys, os
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from benchmarks._timing import slope_time
        import time as _t
        calls = []

        def run_fenced(n):   # 5 ms/step + 50 ms fixed "readback"
            calls.append(n)
            _t.sleep(0.005 * n + 0.05)

        per, tag = slope_time(run_fenced, 4, 12)
        assert tag == "slope"
        assert calls == [4, 12]
        assert 0.004 < per < 0.008  # latency cancelled

    def test_fallback_marked(self):
        from benchmarks._timing import slope_time
        per, tag = slope_time(lambda n: None, 1, 2)
        assert tag in ("slope", "mean_fallback")  # ~0-time runs: either

    def test_rejects_bad_counts(self):
        import pytest as _pytest
        from benchmarks._timing import slope_time
        with _pytest.raises(ValueError):
            slope_time(lambda n: None, 5, 5)
        with _pytest.raises(ValueError):
            slope_time(lambda n: None, 0, 5)
