"""ISSUE 20 np4 convergence acceptance (slow tier): the tentpole cell
— int8-quantized Adasum transport with per-hop error feedback — under
a REAL 4-process ``hvdrun`` launch.

The bar: every rank records the SAME loss curve (the engine-negotiated
quantized exchange kept real processes together), the curve descends,
and the launcher exits cleanly within the timeout. Driven through the
tools/converge.py CLI so the CLI contract (JSON verdict on stdout,
exit code) is covered by the same run — the wiring the chaos soak
acceptance tests use."""
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.mark.slow
def test_np4_int8_adasum_converge_acceptance(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "converge.py"),
         "--np", "4", "--model", "gpt_tiny", "--fmt", "int8",
         "--op", "adasum", "--out", str(tmp_path), "--timeout", "720"],
        env=env, capture_output=True, text=True, timeout=780)
    assert out.stdout.strip(), out.stderr[-3000:]
    verdict = json.loads(out.stdout)
    detail = json.dumps(verdict, indent=2, sort_keys=True)[:3000]
    assert verdict["no_deadlock"], detail
    assert verdict["curves_complete"], detail
    assert verdict["curves_identical"], detail
    assert verdict["descended"], detail
    assert verdict["cell"] == "int8xadasumxdirect", detail
    assert verdict["ok"] and out.returncode == 0, detail
