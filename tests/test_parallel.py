"""TP / SP (ring + Ulysses) / EP / PP correctness on the 8-device CPU mesh.

No reference analog (Horovod is DP-only, SURVEY §2.6); oracles are
single-device dense implementations."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import sp as sp_lib
from horovod_tpu.parallel.mesh_utils import make_mesh


def _qkv(B=2, H=4, S=32, D=8, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randn(B, H, S, D).astype(np.float32) * 0.3 for _ in range(3)]


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, hvd, causal):
        q, k, v = _qkv()
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.ring_attention(a, b, c, "sp",
                                                  causal=causal),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
        out = np.asarray(f(q, k, v))
        ref = np.asarray(sp_lib.attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_long_sequence_small_local(self, hvd):
        # 8 devices x 16 local = 128 positions
        q, k, v = _qkv(B=1, H=2, S=128, D=4, seed=3)
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.ring_attention(a, b, c, "sp"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
        out = np.asarray(f(q, k, v))
        ref = np.asarray(sp_lib.attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, hvd, causal):
        q, k, v = _qkv(B=2, H=8, S=32, D=8)  # H divisible by sp=8
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.ulysses_attention(a, b, c, "sp",
                                                     causal=causal),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
        out = np.asarray(f(q, k, v))
        ref = np.asarray(sp_lib.attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


class TestTensorParallel:
    def test_column_then_row_matches_dense(self, hvd):
        from horovod_tpu.parallel.tp import (column_parallel_dense,
                                             row_parallel_dense)
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w1 = rng.randn(16, 32).astype(np.float32)
        w2 = rng.randn(32, 16).astype(np.float32)
        mesh = make_mesh(tp=8)

        def blk(x, w1l, w2l):
            h = column_parallel_dense(x, w1l)
            h = jax.nn.relu(h)
            return row_parallel_dense(h, w2l, axis_name="tp")

        f = jax.jit(jax.shard_map(
            blk, mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp", None)),
            out_specs=P()))
        out = np.asarray(f(x, w1, w2))
        ref = np.maximum(x @ w1, 0) @ w2
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_partition_rules_paths(self, hvd):
        from horovod_tpu.parallel.tp import gpt_partition_rules
        rules = gpt_partition_rules()
        assert rules.spec_for("transformer/layers_0/attn/qkv/kernel") == \
            P(None, "tp")
        assert rules.spec_for("layers_3/mlp/down/kernel") == P("tp", None)
        assert rules.spec_for("ln_f/scale") == P()


class TestExpertParallel:
    def test_moe_matches_per_token_oracle(self, hvd):
        from horovod_tpu.parallel.ep import moe_layer, top1_route
        rng = np.random.RandomState(0)
        n, e_local, T_local, D = 8, 1, 16, 8
        E = n * e_local
        x = rng.randn(n * T_local, D).astype(np.float32)
        router_w = rng.randn(D, E).astype(np.float32)
        # expert = scale by (e+2)
        expert_scales = np.arange(2, 2 + E, dtype=np.float32)

        def expert_fn(scale, tokens):
            return tokens * scale

        mesh = make_mesh(ep=8)
        f = jax.jit(jax.shard_map(
            lambda xs, ps: moe_layer(xs, jnp.asarray(router_w), expert_fn,
                                     ps, axis_name="ep",
                                     capacity_factor=2.0),
            mesh=mesh,
            in_specs=(P("ep"), P("ep")),
            out_specs=P("ep")))
        out = np.asarray(f(x, expert_scales.reshape(E, 1)[..., 0]))

        # oracle: per-shard independent routing with the same capacity
        capacity = max(1, int(2.0 * T_local / E))
        expect = np.zeros_like(x)
        for s in range(n):
            blk = x[s * T_local:(s + 1) * T_local]
            d, c = top1_route(jnp.asarray(blk @ router_w), E, capacity)
            d, c = np.asarray(d), np.asarray(c)
            for t in range(T_local):
                e = d[t].sum(axis=-1).argmax()
                if d[t].sum() > 0:
                    gate = c[t].sum()
                    expect[s * T_local + t] = blk[t] * expert_scales[e] * gate
        np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


class TestZigzagRing:
    def test_shard_roundtrip(self, hvd):
        x = np.arange(2 * 3 * 32 * 4).reshape(2, 3, 32, 4) \
            .astype(np.float32)
        z = sp_lib.zigzag_shard(jnp.asarray(x), 8)
        assert not np.array_equal(np.asarray(z), x)
        np.testing.assert_array_equal(
            np.asarray(sp_lib.zigzag_unshard(z, 8)), x)

    @pytest.mark.parametrize("impl", ["lax", "flash_interpret"])
    def test_matches_dense_causal(self, hvd, impl):
        q, k, v = _qkv()
        n = 8
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        qz, kz, vz = [sp_lib.zigzag_shard(jnp.asarray(t), n)
                      for t in (q, k, v)]
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.zigzag_ring_attention(
                a, b, c, "sp", causal=True, impl=impl),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=(impl == "lax")))
        out = sp_lib.zigzag_unshard(f(qz, kz, vz), n)
        ref = sp_lib.attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-4)

    def test_noncausal_delegates_to_ring(self, hvd):
        q, k, v = _qkv()
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.zigzag_ring_attention(
                a, b, c, "sp", causal=False),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
        out = f(*[jnp.asarray(t) for t in (q, k, v)])
        ref = sp_lib.attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-4)

    def test_gqa_kv_width(self, hvd):
        rng = np.random.RandomState(5)
        B, H, Hkv, S, D = 2, 4, 2, 32, 8
        q = (rng.randn(B, H, S, D) * 0.3).astype(np.float32)
        k = (rng.randn(B, Hkv, S, D) * 0.3).astype(np.float32)
        v = (rng.randn(B, Hkv, S, D) * 0.3).astype(np.float32)
        n = 8
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)
        qz = sp_lib.zigzag_shard(jnp.asarray(q), n)
        kz = sp_lib.zigzag_shard(jnp.asarray(k), n)
        vz = sp_lib.zigzag_shard(jnp.asarray(v), n)
        f = jax.jit(jax.shard_map(
            lambda a, b, c: sp_lib.zigzag_ring_attention(
                a, b, c, "sp", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))
        out = sp_lib.zigzag_unshard(f(qz, kz, vz), n)
        kf, vf = sp_lib.expand_kv_heads(jnp.asarray(k), jnp.asarray(v),
                                        H // Hkv)
        ref = sp_lib.attention_reference(jnp.asarray(q), kf, vf,
                                         causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=2e-4)

    @pytest.mark.parametrize("impl", ["lax", "flash_interpret"])
    def test_grads_match_dense(self, hvd, impl):
        q, k, v = _qkv(B=1, H=2, S=32, D=8)
        n = 8
        mesh = make_mesh(sp=8)
        spec = P(None, None, "sp", None)

        def zig_loss(q_, k_, v_):
            qz, kz, vz = [sp_lib.zigzag_shard(t, n)
                          for t in (q_, k_, v_)]
            f = jax.shard_map(
                lambda a, b, c: sp_lib.zigzag_ring_attention(
                    a, b, c, "sp", causal=True, impl=impl),
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=(impl == "lax"))
            out = sp_lib.zigzag_unshard(f(qz, kz, vz), n)
            return (out.astype(jnp.float32) ** 2).sum()

        def ref_loss(q_, k_, v_):
            out = sp_lib.attention_reference(q_, k_, v_, causal=True)
            return (out.astype(jnp.float32) ** 2).sum()

        args = [jnp.asarray(t) for t in (q, k, v)]
        gz = jax.jit(jax.grad(zig_loss, argnums=(0, 1, 2)))(*args)
        gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(*args)
        for a, b in zip(gz, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)


class TestPipeline:
    def test_gpipe_matches_sequential(self, hvd):
        from horovod_tpu.parallel.pp import gpipe_and_return
        rng = np.random.RandomState(0)
        n, M, mb, D = 8, 4, 2, 8
        # stage s: x -> tanh(x @ W_s)
        Ws = rng.randn(n, D, D).astype(np.float32) * 0.5
        x = rng.randn(M, mb, D).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        mesh = make_mesh(pp=8)
        f = jax.jit(jax.shard_map(
            lambda w, xs: gpipe_and_return(stage_fn, w[0], xs, "pp"),
            mesh=mesh,
            in_specs=(P("pp"), P()),
            out_specs=P()))
        out = np.asarray(f(Ws, x))

        ref = x.copy()
        for s in range(n):
            ref = np.tanh(ref @ Ws[s])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("M", [2, 6])
    def test_1f1b_matches_sequential_grad(self, hvd, M):
        """1F1B loss + per-stage grads == non-pipelined autodiff, incl.
        M < S (more stages than microbatches) and M > S."""
        from horovod_tpu.parallel.pp import pipeline_1f1b
        rng = np.random.RandomState(1)
        n, mb, D = 4, 2, 6
        Ws = rng.randn(n, D, D).astype(np.float32) * 0.5
        xs = rng.randn(M, mb, D).astype(np.float32)
        ys = rng.randn(M, mb, D).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        mesh = make_mesh(pp=4, devices=jax.devices()[:4])

        def wrapped(w, a, b):
            loss, g = pipeline_1f1b(stage_fn, w[0], a, b, loss_fn, "pp")
            return loss, g[None]          # re-add the stage axis

        f = jax.jit(jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp"))))
        loss, grads = f(Ws, xs, ys)
        grads = np.asarray(grads)              # [n, D, D] — stage-sharded

        def ref_loss(ws):
            h = jnp.asarray(xs)
            for s in range(n):
                h = jnp.tanh(h @ ws[s])
            # mean over microbatches of per-microbatch mean loss
            return jnp.mean(
                jax.vmap(loss_fn)(h, jnp.asarray(ys)))

        ref_l, ref_g = jax.value_and_grad(ref_loss)(jnp.asarray(Ws))
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(grads, np.asarray(ref_g),
                                   rtol=1e-4, atol=1e-5)

    def test_1f1b_lm_embed_and_head(self, hvd):
        """The full-LM hooks: embedding outside the pipeline (input
        grads returned), head inside the loss (head grads returned) —
        every gradient matches sequential autodiff."""
        from horovod_tpu.parallel.pp import pipeline_1f1b
        rng = np.random.RandomState(3)
        n, M, mb, S, D, V = 4, 4, 2, 8, 6, 12
        Ws = (rng.randn(n, D, D) * 0.5).astype(np.float32)
        emb = (rng.randn(V, D) * 0.5).astype(np.float32)
        head = (rng.randn(D, V) * 0.5).astype(np.float32)
        toks = rng.randint(0, V, (M, mb, S)).astype(np.int32)
        tgts = rng.randint(0, V, (M, mb, S)).astype(np.int32)

        def stage_fn(w, x):
            return x + jnp.tanh(x @ w)          # residual block

        def loss_fn(h, y, t):
            logp = jax.nn.log_softmax(y @ h)
            return -jnp.mean(
                jnp.take_along_axis(logp, t[..., None], axis=-1))

        mesh = make_mesh(pp=4, devices=jax.devices()[:4])

        def run(w, e, h):
            xs = e[jnp.asarray(toks)]           # [M, mb, S, D]
            loss, g, aux = pipeline_1f1b(
                stage_fn, w[0], xs, jnp.asarray(tgts), loss_fn, "pp",
                head_params=h, return_input_grads=True)
            demb = jnp.zeros_like(e).at[jnp.asarray(toks).ravel()].add(
                aux["input_grads"].reshape(-1, e.shape[1]))
            return loss, g[None], aux["head_grads"], demb

        f = jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp"), P(), P())))
        loss, gW, gH, gE = f(Ws, emb, head)

        def ref(w, e, h):
            x = e[jnp.asarray(toks)]
            for s in range(n):
                x = stage_fn(w[s], x)
            per_mb = jax.vmap(lambda y, t: loss_fn(h, y, t))(
                x, jnp.asarray(tgts))
            return per_mb.mean()

        ref_l, (rW, rE, rH) = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(jnp.asarray(Ws), jnp.asarray(emb),
                                    jnp.asarray(head))
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gW), np.asarray(rW),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gH), np.asarray(rH),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gE), np.asarray(rE),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("M", [2, 4])
    def test_interleaved_1f1b_matches_sequential(self, hvd, M):
        """Virtual-stage (Megatron interleaved) schedule: n=4 devices x
        V=2 chunks = 8 global stages; loss + per-chunk grads + head +
        input grads all match sequential autodiff."""
        from horovod_tpu.parallel.pp import pipeline_interleaved_1f1b
        rng = np.random.RandomState(11)
        n, V, mb, D = 4, 2, 2, 6
        S_total = n * V
        Wg = (rng.randn(S_total, D, D) * 0.5).astype(np.float32)
        # device i owns global stages (i, i+n) -> stack [n, V, D, D]
        Wdev = np.stack([Wg[[i, i + n]] for i in range(n)])
        xs = rng.randn(M, mb, D).astype(np.float32)
        ys = rng.randn(M, mb, D).astype(np.float32)
        head = (rng.randn(D, D) * 0.5).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_fn(h, y, t):
            return jnp.mean((y @ h - t) ** 2)

        mesh = make_mesh(pp=4, devices=jax.devices()[:4])

        def run(w, a, b, h):
            loss, g, aux = pipeline_interleaved_1f1b(
                stage_fn, w[0], a, b, loss_fn, "pp",
                head_params=h, return_input_grads=True)
            return loss, g[None], aux["head_grads"], aux["input_grads"]

        f = jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P("pp"), P(), P())))
        loss, gW, gH, gX = f(Wdev, xs, ys, head)

        def ref(wg, h, xin):
            x = xin
            for s in range(S_total):
                x = stage_fn(wg[s], x)
            return jax.vmap(lambda y, t: loss_fn(h, y, t))(
                x, jnp.asarray(ys)).mean()

        ref_l, (rWg, rH, rX) = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(jnp.asarray(Wg), jnp.asarray(head),
                                    jnp.asarray(xs))
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        rWdev = np.stack([np.asarray(rWg)[[i, i + n]] for i in range(n)])
        np.testing.assert_allclose(np.asarray(gW), rWdev,
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gH), np.asarray(rH),
                                   rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gX), np.asarray(rX),
                                   rtol=2e-4, atol=1e-5)

    def test_interleaved_waves_m_gt_s(self, hvd):
        """M=8 over 4 stages: two waves, losses averaged, grads summed
        to the exact mean-over-M objective."""
        from horovod_tpu.parallel.pp import pipeline_interleaved_waves
        rng = np.random.RandomState(13)
        n, V, M, mb, D = 4, 2, 8, 2, 6
        S_total = n * V
        Wg = (rng.randn(S_total, D, D) * 0.5).astype(np.float32)
        Wdev = np.stack([Wg[[i, i + n]] for i in range(n)])
        xs = rng.randn(M, mb, D).astype(np.float32)
        ys = rng.randn(M, mb, D).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        mesh = make_mesh(pp=4, devices=jax.devices()[:4])

        def run(w, a, b):
            loss, g = pipeline_interleaved_waves(
                stage_fn, w[0], a, b, loss_fn, "pp")
            return loss, g[None]

        f = jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P("pp"))))
        loss, gW = f(Wdev, xs, ys)

        def ref(wg):
            x = jnp.asarray(xs)
            for s in range(S_total):
                x = stage_fn(wg[s], x)
            return jax.vmap(loss_fn)(x, jnp.asarray(ys)).mean()

        ref_l, rWg = jax.value_and_grad(ref)(jnp.asarray(Wg))
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        rWdev = np.stack([np.asarray(rWg)[[i, i + n]] for i in range(n)])
        np.testing.assert_allclose(np.asarray(gW), rWdev,
                                   rtol=2e-4, atol=1e-5)

    def test_1f1b_with_fsdp_sharded_stage_params(self, hvd):
        """ZeRO inside the pipeline: stage params shard over dp, the
        stage_fn all-gathers them per use and the all_gather's vjp
        reduce-scatters the grads — pp=4 x dp=2 matches full-batch
        sequential autodiff with each dp member holding half of each
        stage's weight."""
        from horovod_tpu.parallel.pp import pipeline_1f1b
        rng = np.random.RandomState(21)
        n, dp, M, mb, D = 4, 2, 4, 2, 6
        Ws = (rng.randn(n, D, D) * 0.5).astype(np.float32)
        B = dp * M
        xs = rng.randn(B, mb, D).astype(np.float32)
        ys = rng.randn(B, mb, D).astype(np.float32)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        mesh = make_mesh(pp=4, dp=2)

        def run(w_shard, a, b):
            # w_shard: this device's [D/dp, D] slice of its stage's W
            def stage_fn(ws, x):
                w_full = lax.all_gather(ws, "dp", axis=0, tiled=True)
                return jnp.tanh(x @ w_full)

            loss, g = pipeline_1f1b(
                stage_fn, w_shard[0], a, b, loss_fn, "pp",
                vary_axes=("dp",))
            # the all_gather vjp reduce-scatters a SUM over dp of the
            # per-shard-batch grads; the mean-over-all-microbatches
            # objective needs the dp mean
            loss = lax.pmean(loss, "dp")
            return loss, g[None] / dp

        f = jax.jit(jax.shard_map(
            run, mesh=mesh,
            in_specs=(P("pp", "dp"), P("dp"), P("dp")),
            out_specs=(P(), P("pp", "dp"))))
        loss, gW = f(Ws, xs, ys)

        def ref(wg):
            x = jnp.asarray(xs)
            for s in range(n):
                x = jnp.tanh(x @ wg[s])
            return jax.vmap(loss_fn)(x, jnp.asarray(ys)).mean()

        ref_l, rW = jax.value_and_grad(ref)(jnp.asarray(Ws))
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gW), np.asarray(rW),
                                   rtol=2e-4, atol=1e-5)

    def test_interleaved_rejects_large_group(self, hvd):
        from horovod_tpu.parallel.pp import pipeline_interleaved_1f1b
        mesh = make_mesh(pp=4, devices=jax.devices()[:4])
        W = np.zeros((4, 2, 4, 4), np.float32)
        xs = np.zeros((6, 2, 4), np.float32)   # M=6 > n=4

        def run(w, a):
            return pipeline_interleaved_1f1b(
                lambda p, x: x, w[0], a, a,
                lambda y, t: jnp.mean(y), "pp")

        with pytest.raises(ValueError, match="waves"):
            jax.jit(jax.shard_map(
                run, mesh=mesh, in_specs=(P("pp"), P()),
                out_specs=(P(), P("pp"))))(W, xs)

    def test_gpt_pp_matches_sequential(self, hvd):
        """The pipelined GPT (models/gpt_pp.py): 1F1B loss and every
        grad family (embed, per-stage blocks, head) == sequential
        autodiff with the same modules and params."""
        from horovod_tpu.models.gpt import GPTConfig
        from horovod_tpu.models.gpt_pp import (EmbedIn, Head,
                                               StageBlocks, gpt_pp_init,
                                               make_gpt_pp_step)
        cfg = GPTConfig(vocab_size=32, num_layers=4, num_heads=2,
                        head_dim=4, max_seq_len=16, dtype=jnp.float32)
        stages, M, mb, seq = 4, 4, 2, 16
        embed_p, stage_p, head_p = gpt_pp_init(
            cfg, stages, jax.random.PRNGKey(0))
        mesh = make_mesh(pp=4, devices=jax.devices()[:4])
        rnp = np.random.RandomState(0)
        toks = rnp.randint(0, 32, (M * mb, seq)).astype(np.int32)
        tgts = rnp.randint(0, 32, (M * mb, seq)).astype(np.int32)

        step = make_gpt_pp_step(cfg, mesh, num_microbatches=M)
        loss, (gE, gS, gH) = step((embed_p, stage_p, head_p), toks, tgts)

        toks_mb = jnp.asarray(toks.reshape(M, mb, seq))
        tgts_mb = jnp.asarray(tgts.reshape(M, mb, seq))
        stage_mod = StageBlocks(cfg, cfg.num_layers // stages)

        def ref(ep, sp, hp):
            x = jax.vmap(lambda t: EmbedIn(cfg).apply(
                {"params": ep}, t))(toks_mb)
            for s in range(stages):
                p_s = jax.tree_util.tree_map(lambda a: a[s], sp)
                x = jax.vmap(lambda xx: stage_mod.apply(
                    {"params": p_s}, xx))(x)

            def mb_loss(y, t):
                logp = jax.nn.log_softmax(
                    Head(cfg).apply({"params": hp}, y))
                return -jnp.mean(
                    jnp.take_along_axis(logp, t[..., None], axis=-1))

            return jax.vmap(mb_loss)(x, tgts_mb).mean()

        ref_l, (rE, rS, rH) = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(embed_p, stage_p, head_p)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        for got, want, name in ((gE, rE, "embed"), (gS, rS, "stage"),
                                (gH, rH, "head")):
            flat_g = jax.tree_util.tree_leaves(got)
            flat_r = jax.tree_util.tree_leaves(want)
            for a, b in zip(flat_g, flat_r):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                    err_msg=f"{name} grads diverge")

    def test_gpt_pp_interleaved_matches_sequential(self, hvd):
        """Pipelined GPT on the interleaved schedule: 2 devices x 2
        virtual chunks = 4 global stages; M=4 > stages exercises the
        wave scan inside make_gpt_pp_step."""
        from horovod_tpu.models.gpt import GPTConfig
        from horovod_tpu.models.gpt_pp import (EmbedIn, Head,
                                               StageBlocks, gpt_pp_init,
                                               make_gpt_pp_step)
        cfg = GPTConfig(vocab_size=32, num_layers=4, num_heads=2,
                        head_dim=4, max_seq_len=16, dtype=jnp.float32)
        stages, V, M, mb, seq = 2, 2, 4, 2, 16
        embed_p, stage_p, head_p = gpt_pp_init(
            cfg, stages, jax.random.PRNGKey(4), virtual=V)
        mesh = make_mesh(pp=2, devices=jax.devices()[:2])
        rnp = np.random.RandomState(5)
        toks = rnp.randint(0, 32, (M * mb, seq)).astype(np.int32)
        tgts = rnp.randint(0, 32, (M * mb, seq)).astype(np.int32)

        step = make_gpt_pp_step(cfg, mesh, num_microbatches=M,
                                virtual=V)
        loss, (gE, gS, gH) = step((embed_p, stage_p, head_p), toks, tgts)

        toks_mb = jnp.asarray(toks.reshape(M, mb, seq))
        tgts_mb = jnp.asarray(tgts.reshape(M, mb, seq))
        stage_mod = StageBlocks(cfg, cfg.num_layers // (stages * V))

        def ref(ep, sp, hp):
            x = jax.vmap(lambda t: EmbedIn(cfg).apply(
                {"params": ep}, t))(toks_mb)
            for s in range(stages * V):   # global stage s = [s%S, s//S]
                p_s = jax.tree_util.tree_map(
                    lambda a: a[s % stages, s // stages], sp)
                x = jax.vmap(lambda xx: stage_mod.apply(
                    {"params": p_s}, xx))(x)

            def mb_loss(y, t):
                logp = jax.nn.log_softmax(
                    Head(cfg).apply({"params": hp}, y))
                return -jnp.mean(
                    jnp.take_along_axis(logp, t[..., None], axis=-1))

            return jax.vmap(mb_loss)(x, tgts_mb).mean()

        ref_l, (rE, rS, rH) = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(embed_p, stage_p, head_p)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        for got, want, name in ((gE, rE, "embed"), (gS, rS, "stage"),
                                (gH, rH, "head")):
            for a, b in zip(jax.tree_util.tree_leaves(got),
                            jax.tree_util.tree_leaves(want)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                    err_msg=f"{name} grads diverge (interleaved)")

    def test_gpt_pp_dp_hybrid_matches_sequential(self, hvd):
        """pp=4 x dp=2: each dp shard pipelines its half of the batch;
        loss and all grads pmean over dp — must equal full-batch
        sequential autodiff."""
        from horovod_tpu.models.gpt import GPTConfig
        from horovod_tpu.models.gpt_pp import (EmbedIn, Head,
                                               StageBlocks, gpt_pp_init,
                                               make_gpt_pp_step)
        cfg = GPTConfig(vocab_size=32, num_layers=4, num_heads=2,
                        head_dim=4, max_seq_len=16, dtype=jnp.float32)
        stages, dp, M, mb, seq = 4, 2, 2, 2, 16
        embed_p, stage_p, head_p = gpt_pp_init(
            cfg, stages, jax.random.PRNGKey(1))
        mesh = make_mesh(pp=4, dp=2)
        rnp = np.random.RandomState(2)
        B = dp * M * mb
        toks = rnp.randint(0, 32, (B, seq)).astype(np.int32)
        tgts = rnp.randint(0, 32, (B, seq)).astype(np.int32)

        step = make_gpt_pp_step(cfg, mesh, num_microbatches=M,
                                dp_axis="dp")
        loss, (gE, gS, gH) = step((embed_p, stage_p, head_p), toks, tgts)

        # oracle: mean over ALL dp*M microbatches, sequential
        toks_mb = jnp.asarray(toks.reshape(dp * M, mb, seq))
        tgts_mb = jnp.asarray(tgts.reshape(dp * M, mb, seq))
        stage_mod = StageBlocks(cfg, cfg.num_layers // stages)

        def ref(ep, sp, hp):
            x = jax.vmap(lambda t: EmbedIn(cfg).apply(
                {"params": ep}, t))(toks_mb)
            for s in range(stages):
                p_s = jax.tree_util.tree_map(lambda a: a[s], sp)
                x = jax.vmap(lambda xx: stage_mod.apply(
                    {"params": p_s}, xx))(x)

            def mb_loss(y, t):
                logp = jax.nn.log_softmax(
                    Head(cfg).apply({"params": hp}, y))
                return -jnp.mean(
                    jnp.take_along_axis(logp, t[..., None], axis=-1))

            return jax.vmap(mb_loss)(x, tgts_mb).mean()

        ref_l, (rE, rS, rH) = jax.value_and_grad(
            ref, argnums=(0, 1, 2))(embed_p, stage_p, head_p)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        for got, want, name in ((gE, rE, "embed"), (gS, rS, "stage"),
                                (gH, rH, "head")):
            for a, b in zip(jax.tree_util.tree_leaves(got),
                            jax.tree_util.tree_leaves(want)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                    err_msg=f"{name} grads diverge (pp x dp)")


class TestGPTModel:
    def test_gpt_dense_forward(self, hvd):
        from horovod_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4,
                        head_dim=8, max_seq_len=64, dtype=jnp.float32)
        model = GPT(cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 16, 64)

    @pytest.mark.parametrize("attention", ["ring", "zigzag"])
    def test_gpt_ring_matches_dense(self, hvd, attention):
        from horovod_tpu.models.gpt import GPT, GPTConfig
        mesh = make_mesh(sp=8)
        tokens = np.random.RandomState(0).randint(
            0, 64, (2, 32)).astype(np.int32)
        cfg_d = GPTConfig(vocab_size=64, num_layers=1, num_heads=4,
                          head_dim=8, max_seq_len=64, dtype=jnp.float32)
        cfg_r = GPTConfig(vocab_size=64, num_layers=1, num_heads=4,
                          head_dim=8, max_seq_len=64, attention=attention,
                          mesh=mesh, dp_axis="none", tp_axis="none",
                          dtype=jnp.float32)
        model_d, model_r = GPT(cfg_d), GPT(cfg_r)
        params = model_d.init(jax.random.PRNGKey(0),
                              jnp.asarray(tokens))["params"]
        out_d = np.asarray(model_d.apply({"params": params},
                                         jnp.asarray(tokens)))
        out_r = np.asarray(model_r.apply({"params": params},
                                         jnp.asarray(tokens)))
        np.testing.assert_allclose(out_r, out_d, rtol=5e-4, atol=5e-4)

    def test_gpt_hybrid_train_step(self, hvd):
        """dp=2 x tp=2 x sp=2 GSPMD train step end-to-end."""
        import optax
        from horovod_tpu.models.gpt import GPT, GPTConfig
        from horovod_tpu.parallel.tp import (gpt_partition_rules,
                                             shard_params)
        from horovod_tpu.training import make_gspmd_train_step
        mesh = make_mesh(dp=2, sp=2, tp=2)
        cfg = GPTConfig(vocab_size=64, num_layers=2, num_heads=4,
                        head_dim=8, max_seq_len=64, attention="ring",
                        mesh=mesh, dtype=jnp.float32)
        model = GPT(cfg)
        tokens = np.random.RandomState(0).randint(
            0, 64, (4, 32)).astype(np.int32)
        targets = np.roll(tokens, -1, axis=1)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(tokens))["params"]
        rules = gpt_partition_rules()
        params = shard_params(params, mesh, rules)
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)
        step = make_gspmd_train_step(model.apply, tx, mesh, rules)
        p, o, loss1 = step(params, opt_state, jnp.asarray(tokens),
                           jnp.asarray(targets))
        p, o, loss2 = step(p, o, jnp.asarray(tokens), jnp.asarray(targets))
        assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
        assert float(loss2) < float(loss1)  # learning on repeated batch
