"""Docs suite sanity: pages exist, internal links resolve, and the API
names the docs show actually exist in the package."""
import os
import re

import pytest

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs")


def test_index_links_resolve():
    index = open(os.path.join(DOCS, "index.md")).read()
    links = re.findall(r"\]\((\w[\w.-]*\.md)\)", index)
    assert len(links) >= 12
    for ln in set(links):
        assert os.path.exists(os.path.join(DOCS, ln)), f"missing {ln}"


def test_all_pages_nonempty():
    pages = [f for f in os.listdir(DOCS) if f.endswith(".md")]
    assert len(pages) >= 13
    for p in pages:
        assert len(open(os.path.join(DOCS, p)).read()) > 400, p


def test_documented_api_exists():
    import horovod_tpu as hvd
    for name in ("init", "allreduce", "allreduce_async", "synchronize",
                 "Checkpointer", "save_checkpoint", "restore_checkpoint",
                 "join", "barrier", "Compression", "DistributedOptimizer",
                 "ProcessSet", "add_process_set", "start_timeline"):
        assert hasattr(hvd, name), name
    from horovod_tpu.training import (make_train_step,           # noqa: F401
                                      make_gspmd_train_step,
                                      init_replicated, shard_batch)
    from horovod_tpu.checkpoint import FileBackedState           # noqa: F401
    from horovod_tpu.ops.cross import (two_level_allreduce,      # noqa: F401
                                       two_level_allgather)
    from horovod_tpu.ops.adasum import adasum_allreduce          # noqa: F401
    import horovod_tpu.interop.haiku as hvd_hk
    assert hasattr(hvd_hk, "make_train_step")
    import horovod_tpu.interop.hf as hvd_hf
    assert hasattr(hvd_hf, "make_finetune_step")
    from horovod_tpu.spark import (FlaxEstimator, TorchEstimator,  # noqa
                                   LocalStore)
    from horovod_tpu.ray import RayExecutor                      # noqa: F401
