"""Disaggregated serving: KV-block migration, wire frames, verdicts.

Tier-1 coverage for serve/disagg.py + serve/kv_migrate.py (the
process-level soak acceptance lives in the slow tier,
tools/serve_soak.py --disagg):

* binary wire frames + the HOROVOD_SERVE_WIRE_MAX_FRAME knob;
* migrated-KV decode BIT-IDENTICAL to colocated prefill+decode across
  {GPT, Llama-GQA} x {greedy, speculative, sampled} x prefix-CoW
  blocks (pack -> install fully in-process — the plan/transport
  split makes the transport swappable);
* corrupt-in-flight caught by the per-block crc BEFORE any token,
  version fencing, reservation-gated install rejection, parked-row
  lifecycle (release + TTL reap);
* the endpoint ops (kv_install dedupe against ladder replays,
  migrate push under serve.migrate chaos);
* evaluate_disagg: green + one red per invariant;
* aggregate_healthz per-pool breakdown (503 only at zero ADMITTING
  capacity);
* the lifted fleet front door: sampled requests routed (no 400) and
  answered identically through a mid-request failover.
"""
import json
import socket
import threading
import time
from types import SimpleNamespace

import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.chaos import inject
from horovod_tpu.chaos.plan import ChaosPlan, PlanError, random_plan
from horovod_tpu.models.gpt import GPT, GPTConfig
from horovod_tpu.models.llama import Llama, LlamaConfig
from horovod_tpu.serve import kv_migrate, wire
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.executor import ShardedExecutor
from horovod_tpu.serve.fleet import (FleetRouter, Replica,
                                     aggregate_healthz)
from horovod_tpu.serve.queue import AdmissionQueue
from horovod_tpu.serve.soak import evaluate_disagg
from horovod_tpu.serve.worker import ReplicaEndpoint

_GPT_KW = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
               max_seq_len=48, dtype=jnp.float32,
               attention_impl="reference")
_PAGED = dict(kv_block_size=4, kv_pool_blocks=32)
_LLAMA_KW = dict(vocab_size=64, num_layers=2, num_heads=4,
                 num_kv_heads=2, head_dim=8, max_seq_len=48,
                 dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _disarm():
    inject.uninstall()
    yield
    inject.uninstall()


@pytest.fixture(scope="module")
def expool():
    """Executor cache: jit caches are per executor, so the module
    shares one per (model, role, tag) and tests build fresh batchers
    over them (the Replica.build discipline)."""
    cache = {}

    def get(model: str, role: str = "target", tag: int = 0):
        key = (model, role, tag)
        if key in cache:
            return cache[key]
        if model == "gpt":
            dec = GPT(GPTConfig(decode=True, **_GPT_KW, **_PAGED))
            draft = GPT(GPTConfig(decode=True, **_GPT_KW))
            params = GPT(GPTConfig(**_GPT_KW)).init(
                jax.random.PRNGKey(0),
                jnp.zeros((2, 8), jnp.int32))["params"]
        else:
            dec = Llama(LlamaConfig(decode=True, **_LLAMA_KW,
                                    **_PAGED))
            draft = Llama(LlamaConfig(decode=True, **_LLAMA_KW))
            params = Llama(LlamaConfig(**_LLAMA_KW)).init(
                jax.random.PRNGKey(0),
                jnp.zeros((2, 8), jnp.int32))["params"]
        model_obj = draft if role == "draft" else dec
        cache[key] = ShardedExecutor(
            model_obj, params, max_batch=4, max_len=48,
            replica_id=tag, role=role)
        return cache[key]

    return get


def _batcher(expool, model="gpt", tag=0, *, spec=False, kv_crc=True,
             prefix=True, max_queue=16):
    q = AdmissionQueue(max_queue=max_queue,
                       default_deadline_ms=20000.0, replica_id=tag)
    b = ContinuousBatcher(
        expool(model, "target", tag), q, buckets=(8,),
        replica_id=tag, kv_crc=kv_crc,
        draft_executor=expool(model, "draft", tag) if spec else None,
        spec_k=3 if spec else 0, prefix_cache=prefix)
    b.warmup()
    return b


def _pack(b, handle, max_new, deadline_ms=20000.0, fid="d0"):
    return kv_migrate.pack_parked(b, handle.rid, fid=fid,
                                  max_new_tokens=max_new,
                                  deadline_ms=deadline_ms)


def _migrate_run(prefill_b, decode_b, prompt, max_new, **sampling):
    """Full in-process disagg leg: hold-prefill, pack, install, decode
    to completion; returns the token stream."""
    h1 = prefill_b.queue.submit(prompt, max_new_tokens=1,
                                hold_kv=True, **sampling)
    prefill_b.run()
    assert h1.status == "ok" and len(h1.tokens) == 1
    header, payload = _pack(prefill_b, h1, max_new,
                            fid=f"d{h1.rid}")
    decode_b.start()
    try:
        outcome, detail, h2 = kv_migrate.install(decode_b, header,
                                                 payload)
        assert outcome == "installed", (outcome, detail)
        assert h2.wait(timeout=30)
    finally:
        decode_b.stop()
    prefill_b.release_parked(h1.rid)
    prefill_b.run()
    assert h2.status == "ok"
    return h2.tokens


# ---------------------------------------------------------------------------
# binary wire frames + the max-frame knob
# ---------------------------------------------------------------------------

class TestWireBinary:
    def test_roundtrip_and_crc(self):
        a, b = socket.socketpair()
        try:
            payload = bytes(range(256)) * 17
            import zlib
            wire.send_bin(a, {"op": "kv_install", "x": 1,
                              "payload_crc": zlib.crc32(payload)},
                          payload)
            obj, got = wire.recv_any(b, timeout=5.0)
            assert obj["x"] == 1 and got == payload
            # plain JSON frames pass through recv_any with payload None
            wire.send_msg(a, {"op": "healthz"})
            obj, got = wire.recv_any(b, timeout=5.0)
            assert obj == {"op": "healthz"} and got is None
        finally:
            a.close()
            b.close()

    def test_frame_crc_catches_wire_corruption(self):
        a, b = socket.socketpair()
        try:
            payload = b"\x00" * 400
            wire.send_bin(a, {"payload_crc": 12345}, payload)
            with pytest.raises(wire.DispatchError, match="crc32"):
                wire.recv_any(b, timeout=5.0)
        finally:
            a.close()
            b.close()

    def test_oversize_names_the_knob(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_WIRE_MAX_FRAME",
                           str(1 << 16))
        wire._reset_max_frame_cache()
        try:
            a, b = socket.socketpair()
            try:
                with pytest.raises(wire.DispatchError,
                                   match="HOROVOD_SERVE_WIRE_MAX_FRAME"):
                    wire.send_bin(a, {}, b"\x00" * (1 << 17))
            finally:
                a.close()
                b.close()
        finally:
            monkeypatch.delenv("HOROVOD_SERVE_WIRE_MAX_FRAME")
            wire._reset_max_frame_cache()

    def test_knob_strict_parse_and_range(self, monkeypatch):
        from horovod_tpu.core.config import Config
        monkeypatch.setenv("HOROVOD_SERVE_WIRE_MAX_FRAME", "huge")
        with pytest.raises(ValueError, match="WIRE_MAX_FRAME"):
            Config.from_env()
        monkeypatch.setenv("HOROVOD_SERVE_WIRE_MAX_FRAME", "1024")
        with pytest.raises(ValueError, match="WIRE_MAX_FRAME"):
            Config.from_env()
        monkeypatch.setenv("HOROVOD_SERVE_WIRE_MAX_FRAME",
                           str(64 << 20))
        assert Config.from_env().serve_wire_max_frame == 64 << 20


# ---------------------------------------------------------------------------
# migrated-KV decode bit-identical to colocated prefill+decode
# ---------------------------------------------------------------------------

class TestMigrationParity:
    @pytest.mark.parametrize("model", ["gpt", "llama"])
    @pytest.mark.parametrize("mode", ["greedy", "spec", "sampled"])
    def test_bit_identical_with_prefix_cow(self, expool, model, mode):
        """Two requests per stack: A seeds the radix prefix cache, B
        shares A's prefix and diverges MID-BLOCK (a CoW block joins
        B's table). Both streams must match the colocated reference
        bit for bit — including B, whose migrated payload carries a
        copy-on-written block. ``spec`` runs greedy speculative
        decoding on the DECODE side only (the drafter re-syncs from
        the migrated prefix via forced feeds; greedy spec is
        bit-identical to target-only greedy by construction)."""
        spec = mode == "spec"
        sampling = ({"temperature": 0.8, "top_p": 0.9, "seed": 123}
                    if mode == "sampled" else {})
        prompt_a = [5, 9, 3, 17, 2, 11, 7]          # blocks: 4 + 3
        prompt_b = prompt_a[:5] + [40, 41]          # diverges mid-blk 2
        # colocated reference (prefill+decode in one batcher)
        ref = _batcher(expool, model, tag=0, spec=spec)
        ha = ref.queue.submit(prompt_a, max_new_tokens=8, **sampling)
        ref.run()
        hb = ref.queue.submit(prompt_b, max_new_tokens=8, **sampling)
        ref.run()
        assert ha.status == "ok" and hb.status == "ok"
        # disaggregated: prefill batcher (no drafter) -> migrate ->
        # decode batcher (drafter when spec)
        pre = _batcher(expool, model, tag=1, spec=False)
        dec = _batcher(expool, model, tag=2, spec=spec)
        toks_a = _migrate_run(pre, dec, prompt_a, 8, **sampling)
        assert toks_a == ha.tokens, (toks_a, ha.tokens)
        toks_b = _migrate_run(pre, dec, prompt_b, 8, **sampling)
        assert toks_b == hb.tokens, (toks_b, hb.tokens)
        # B's prefill really did hit the prefix cache (CoW exercised)
        assert pre.prefix is not None and pre.prefix.hits >= 1


# ---------------------------------------------------------------------------
# integrity: corrupt-in-flight, version fence, reservation gate, parking
# ---------------------------------------------------------------------------

class TestMigrationIntegrity:
    def _packet(self, expool, tag, max_new=10):
        pre = _batcher(expool, "gpt", tag=tag)
        h = pre.queue.submit([5, 9, 3, 17, 2], max_new_tokens=1,
                             hold_kv=True)
        pre.run()
        header, payload = _pack(pre, h, max_new, fid=f"t{tag}")
        return pre, h, header, payload

    def test_corrupt_in_flight_caught_before_any_token(self, expool):
        _, _, header, payload = self._packet(expool, 1)
        bad = bytearray(payload)
        bad[13] ^= 0x10
        dec = _batcher(expool, "gpt", tag=2)
        outcome, detail, handle = kv_migrate.install(
            dec, header, bytes(bad), timeout_s=1.0)
        assert outcome == "corrupt" and handle is None
        assert dec.migrate_corrupt_detected == 1
        assert dec.migrations_in == 0 and not dec._active

    def test_truncated_payload_is_corrupt(self, expool):
        _, _, header, payload = self._packet(expool, 1)
        dec = _batcher(expool, "gpt", tag=2)
        outcome, _, _ = kv_migrate.install(dec, header,
                                           payload[:-8], timeout_s=1.0)
        assert outcome == "corrupt"

    def test_version_fence_refuses_mismatch(self, expool):
        _, _, header, payload = self._packet(expool, 1)
        dec = _batcher(expool, "gpt", tag=2)
        stale = dict(header, weights_version=41)   # decode runs None
        ent = dec.submit_migrated(
            stale, kv_migrate.unpack_blocks(stale, payload))
        dec.run()
        assert ent["outcome"][0] == "version_mismatch"
        assert dec.migrations_in == 0 and not dec._active

    def test_packet_stamps_prefill_version_not_pack_version(
            self, expool):
        """A hot swap landing between prefill and pack must fence the
        packet OUT: the stamped version is the one the PREFILL ran
        under, not whatever the executor serves at pack time."""
        pre = _batcher(expool, "gpt", tag=1)
        h = pre.queue.submit([5, 9, 3], max_new_tokens=1, hold_kv=True)
        pre.run()
        ex = pre.executor
        ran_under = ex.last_step_version
        ex.params_version = 7          # a swap landed after the park
        try:
            header, _ = _pack(pre, h, 8)
        finally:
            ex.params_version = ran_under
        assert header["weights_version"] == ran_under != 7

    def test_reservation_gated_rejection(self, expool):
        """An install that would starve admitted sequences is refused
        with a structured retry hint — the same can_admit gate local
        newcomers pass through."""
        pre, h, header, payload = self._packet(expool, 1, max_new=40)
        dec = _batcher(expool, "gpt", tag=2)
        # ... with its pool mostly RESERVED by local admissions
        # (3 rows x ~10-block worst case against a 32-block pool)
        for _ in range(3):
            dec.queue.submit(list(range(1, 8)), max_new_tokens=30)
        dec.step()     # admit + reserve their worst-case growth
        assert dec.kv.reserved_total() > 0
        ent = dec.submit_migrated(
            header, kv_migrate.unpack_blocks(header, payload))
        dec.step()     # the install decision, on this thread
        outcome, detail = ent["outcome"]
        assert outcome == "rejected" and detail is not None
        assert dec.migrate_rejects == 1

    def test_release_and_ttl_reap(self, expool):
        pre = _batcher(expool, "gpt", tag=1)
        h1 = pre.queue.submit([1, 2, 3], max_new_tokens=1,
                              hold_kv=True)
        h2 = pre.queue.submit([4, 5, 6], max_new_tokens=1,
                              hold_kv=True, deadline_ms=50.0)
        pre.run()
        assert len(pre.parked) == 2
        in_use = pre.kv.pool.in_use()
        # explicit release frees the row on the next iteration
        pre.release_parked(h1.rid)
        pre.run()
        assert h1.rid not in pre.parked
        assert pre.kv.pool.in_use() < in_use
        assert kv_migrate.pack_parked(pre, h1.rid, fid="x",
                                      max_new_tokens=4,
                                      deadline_ms=100.0) is None
        # the TTL reaper frees an abandoned parked row past its
        # deadline + grace (the router died mid-orchestration)
        pre.parked_grace_s = 0.0
        time.sleep(0.08)
        pre.step()
        assert h2.rid not in pre.parked and pre.parked_reaped == 1

    def test_hold_kv_resolves_without_blocking_decode(self, expool):
        """A parked sequence must not hold a DECODE row hostage: the
        row leaves _active at park, so max_batch stays available."""
        pre = _batcher(expool, "gpt", tag=1)
        h = pre.queue.submit([1, 2, 3], max_new_tokens=1, hold_kv=True)
        pre.run()
        assert h.status == "ok" and not pre._active
        assert len(pre.parked) == 1


# ---------------------------------------------------------------------------
# endpoint ops: kv_install dedupe, migrate push under chaos
# ---------------------------------------------------------------------------

class TestEndpointMigration:
    def _endpoint(self, expool, tag):
        b = _batcher(expool, "gpt", tag=tag)
        b.start()
        ep = ReplicaEndpoint(b, rid=tag).start()
        return SimpleNamespace(b=b, ep=ep)

    def test_kv_install_replay_deduped(self, expool):
        pre = _batcher(expool, "gpt", tag=1)
        h = pre.queue.submit([5, 9, 3, 17, 2], max_new_tokens=1,
                             hold_kv=True)
        pre.run()
        header, payload = _pack(pre, h, 6, fid="dd1")
        dec = self._endpoint(expool, 2)
        try:
            for i in range(2):
                s = wire.connect(dec.ep.address, timeout=2.0)
                try:
                    wire.send_bin(s, header, payload)
                    ack = wire.recv_msg(s, timeout=20.0)
                finally:
                    s.close()
                assert ack["ack"] == "installed"
                if i == 1:
                    assert ack["dedupe"] is True
            assert dec.b.migrations_in == 1   # installed exactly once
            assert dec.ep.dedupe_hits == 1
            # the result op serves the finished stream (and replays
            # from the cache)
            for _ in range(2):
                s = wire.connect(dec.ep.address, timeout=2.0)
                try:
                    wire.send_msg(s, {"op": "result", "fid": "dd1",
                                      "deadline_ms": 10000.0})
                    ack = wire.recv_msg(s, timeout=5.0)
                    assert ack["ack"] == "accepted"
                    reply = wire.recv_msg(s, timeout=20.0)
                finally:
                    s.close()
                assert reply["status"] == "ok"
                assert len(reply["tokens"]) == 6
            # unknown fid is a structured miss, not a hang
            s = wire.connect(dec.ep.address, timeout=2.0)
            try:
                wire.send_msg(s, {"op": "result", "fid": "nope"})
                assert wire.recv_msg(s, timeout=5.0)["ack"] == \
                    "unknown_fid"
            finally:
                s.close()
        finally:
            dec.ep.close()
            dec.b.stop()

    def test_push_chaos_corrupt_and_conn_reset(self, expool):
        """serve.migrate chaos at the push: a corrupt is caught by the
        BLOCK crc on arrival (frame crc deliberately passes), a
        conn_reset after the frame lands is absorbed by the ladder
        with the replay served the deduped install ack."""
        pre = _batcher(expool, "gpt", tag=1)
        for fid, kind, at in (("c1", "corrupt", 0),
                              ("c2", "conn_reset", 0)):
            h = pre.queue.submit([5, 9, 3], max_new_tokens=1,
                                 hold_kv=True)
            pre.run()
            header, payload = _pack(pre, h, 6, fid=fid)
            if kind == "corrupt":
                plan = ChaosPlan.from_dict({"seed": 3, "faults": [
                    {"rank": 0, "site": "serve.migrate",
                     "kind": "corrupt", "at": at}]})
            else:
                plan = ChaosPlan.from_dict({"seed": 3, "faults": [
                    {"rank": 0, "site": "serve.migrate",
                     "kind": "conn_reset", "at": at}]})
            inject.install(plan, rank=0)
            dec = self._endpoint(expool, 2)
            try:
                ack = kv_migrate.push(dec.ep.address, header, payload)
                if kind == "corrupt":
                    assert ack["ack"] == "migrate_corrupt"
                    assert dec.b.migrate_corrupt_detected == 1
                    assert dec.b.migrations_in == 0
                else:
                    # the frame landed, the ack was severed: the
                    # ladder replay hits the install dedupe
                    assert ack["ack"] == "installed"
                    assert ack["dedupe"] is True
                    assert dec.b.migrations_in == 1
            finally:
                dec.ep.close()
                dec.b.stop()
                inject.uninstall()
                pre.release_parked(h.rid)
                pre.run()


# ---------------------------------------------------------------------------
# the disagg verdict: green + one red per invariant
# ---------------------------------------------------------------------------

def _disagg_fixture():
    plan = random_plan(7, 3, 240, profile="disagg", prefill=2)
    kill = next(f for f in plan.faults if f.kind == "crash")
    victim = kill.peer
    records = [{"fid": i, "t0": 1.0 + i, "t1": 1.05 + i,
                "status": "ok", "latency_ms": 50.0,
                "retry_after_ms": None, "resolutions": 1}
               for i in range(30)]
    events = [
        {"kind": "chaos", "fault": "crash", "site": "serve.proc",
         "peer": victim, "t": 100.0},
        {"kind": "fleet", "event": "eject", "replica": victim,
         "t": 101.0},
        {"kind": "fleet", "event": "readmit", "replica": victim,
         "weights_version": 2, "t": 108.0},
    ]
    stats = {
        "replicas_up": 3, "inflight": 0, "failovers": 1,
        "respawns": 1, "duplicates_suppressed": 0,
        "replicas": {r: {"weights_version": 2} for r in range(3)},
    }
    return plan, records, events, stats


def _eval_disagg(plan, records, events, stats, **kw):
    base = dict(replicas=3, suspect_s=1.0, slo_p99_ms=15000.0,
                slo_error_rate=0.02, recovery_window_s=6.0,
                newest_version=2, migrations_in=40,
                migrate_absorbed=1, migrate_corrupt_detected=2,
                reprefills=1)
    base.update(kw)
    return evaluate_disagg(records, events, plan, stats, **base)


class TestDisaggVerdict:
    def test_green(self):
        v = _eval_disagg(*_disagg_fixture())
        assert v["migrations_ok"] is True
        assert v["migrate_corrupt_caught"] is True
        assert v["migrate_blips_recovered"] is True
        assert v["failovers_only_kills"] is True
        assert v["respawned_on_newest"] is True
        assert v["ok"] is True, json.dumps(v, indent=2, default=str)

    def test_red_no_migrations(self):
        v = _eval_disagg(*_disagg_fixture(), migrations_in=0)
        assert v["migrations_ok"] is False and v["ok"] is False

    def test_red_corrupt_not_caught(self):
        v = _eval_disagg(*_disagg_fixture(),
                         migrate_corrupt_detected=0)
        assert v["migrate_corrupt_caught"] is False
        assert v["ok"] is False

    def test_red_blip_not_recovered(self):
        v = _eval_disagg(*_disagg_fixture(), migrate_absorbed=0,
                         reprefills=0)
        assert v["migrate_blips_recovered"] is False
        assert v["ok"] is False

    def test_red_migration_chaos_escalated_to_failover(self):
        plan, records, events, stats = _disagg_fixture()
        v = _eval_disagg(plan, records, events,
                         dict(stats, failovers=2))
        assert v["failovers_only_kills"] is False and v["ok"] is False

    def test_red_prefill_respawn_on_stale_weights(self):
        plan, records, events, stats = _disagg_fixture()
        events = [dict(e) for e in events]
        for e in events:
            if e.get("event") == "readmit":
                e["weights_version"] = 1
        v = _eval_disagg(plan, records, events, stats)
        assert v["respawned_on_newest"] is False and v["ok"] is False

    def test_red_unbounded_prefill_failover(self):
        plan, records, events, stats = _disagg_fixture()
        events = [dict(e) for e in events]
        for e in events:
            if e.get("event") == "eject":
                e["t"] = 103.5
        v = _eval_disagg(plan, records, events, stats)
        assert v["failover_bounded"] is False and v["ok"] is False


class TestDisaggPlan:
    def test_deterministic_and_composed(self):
        p1 = random_plan(9, 3, 120, profile="disagg", prefill=2)
        p2 = random_plan(9, 3, 120, profile="disagg", prefill=2)
        assert p1.to_json() == p2.to_json()
        sites = {(f.site, f.kind) for f in p1.faults}
        assert ("serve.proc", "crash") in sites
        assert ("serve.migrate", "conn_reset") in sites
        assert ("serve.migrate", "corrupt") in sites
        kill = next(f for f in p1.faults if f.kind == "crash")
        assert 0 <= kill.peer < 2          # a PREFILL replica
        for f in p1.faults:
            if f.site == "serve.migrate":
                assert f.peer == 2         # the decode replica

    def test_fail_fast(self):
        with pytest.raises(PlanError, match="prefill"):
            random_plan(9, 2, 120, profile="disagg", prefill=1)
        with pytest.raises(PlanError, match="decode"):
            random_plan(9, 2, 120, profile="disagg", prefill=2)
        with pytest.raises(PlanError, match="disagg"):
            random_plan(9, 3, 120, profile="serve", prefill=2)


# ---------------------------------------------------------------------------
# per-pool healthz: 503 only at zero ADMITTING capacity
# ---------------------------------------------------------------------------

class TestHealthzPools:
    def _infos(self, pre_free, dec_free):
        return {
            0: {"state": "up", "up": True, "draining": False,
                "queue_depth": 0, "weights_version": 1, "restarts": 0,
                "queue_free": pre_free, "kv_blocks_total": 32,
                "kv_blocks_in_use": 0},
            1: {"state": "up", "up": True, "draining": False,
                "queue_depth": 0, "weights_version": 1, "restarts": 0,
                "queue_free": dec_free, "kv_blocks_total": 32,
                "kv_blocks_in_use": 30},
        }

    def _pools(self):
        return {"prefill": {"replicas": [0], "admitting": True},
                "decode": {"replicas": [1], "admitting": False,
                           "migration_backlog": 3}}

    def test_decode_saturation_degrades_not_503(self):
        out = aggregate_healthz(self._infos(8, 0), draining=False,
                                retry_after_ms=250.0,
                                pools=self._pools())
        assert out["ok"] is True               # prefill still admits
        assert out["degraded"] == ["decode"]
        assert out["pools"]["decode"]["migration_backlog"] == 3
        assert out["pools"]["prefill"]["admitting"] is True

    def test_zero_prefill_capacity_is_503(self):
        out = aggregate_healthz(self._infos(0, 8), draining=False,
                                retry_after_ms=250.0,
                                pools=self._pools())
        assert out["ok"] is False              # admitting pool is full
        assert "prefill" in out["degraded"]

    def test_draining_is_503_and_poolless_unchanged(self):
        out = aggregate_healthz(self._infos(8, 8), draining=True,
                                retry_after_ms=250.0,
                                pools=self._pools())
        assert out["ok"] is False
        legacy = aggregate_healthz(self._infos(8, 8), draining=False,
                                   retry_after_ms=250.0)
        assert legacy["ok"] is True and "pools" not in legacy


# ---------------------------------------------------------------------------
# fleet front door: sampled requests routed, failover-identical
# ---------------------------------------------------------------------------

class TestSampledFleet:
    def _router(self, expool, tags):
        reps = [Replica(t, expool("gpt", "target", t), buckets=(8,),
                        max_queue=16, deadline_ms=20000.0,
                        kv_crc=False, spec_k=0, prefix_cache=False)
                for t in tags]
        return FleetRouter(reps, interval_s=0.05, suspect_s=0.2,
                           auto_restart=False)

    def test_sampled_identical_through_mid_request_failover(
            self, expool):
        """THE regression for the lifted greedy-only restriction: a
        sampled request re-dispatched by a mid-request failover
        answers exactly what the no-failover run answers — per-row
        seeded streams replay deterministically from counter 0."""
        sampling = dict(temperature=0.9, top_p=0.85, seed=77)
        prompt = [5, 9, 3, 17, 2]
        ref_router = self._router(expool, (0, 1)).start()
        try:
            href = ref_router.submit(prompt, max_new_tokens=12,
                                     **sampling)
            assert href.wait(timeout=30) and href.status == "ok"
        finally:
            ref_router.close()
        router = self._router(expool, (0, 1)).start()
        try:
            h = router.submit(prompt, max_new_tokens=12, **sampling)
            with router._lock:
                tr = router._inflight.get(h.fid)
            if tr is not None and tr.rid is not None:
                router._eject(tr.rid, "test: mid-request failover")
            assert h.wait(timeout=30)
            assert h.status == "ok"
            assert h.tokens == href.tokens, (h.tokens, href.tokens)
        finally:
            router.close()

    def test_fleet_front_door_serves_sampled(self, expool):
        """The structured 400 for temperature > 0 is GONE: the fleet
        HTTP face routes sampled requests (and still 400s malformed
        sampling values at the door)."""
        import http.client

        from horovod_tpu.serve.http import make_fleet_server
        router = self._router(expool, (0, 1)).start()
        srv = make_fleet_server(router)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        host, port = srv.server_address[:2]
        try:
            conn = http.client.HTTPConnection(host, port, timeout=30)
            body = json.dumps({"tokens": [5, 9, 3], "max_new_tokens": 6,
                               "temperature": 0.7, "seed": 5})
            conn.request("POST", "/generate", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            assert resp.status == 200, out
            assert len(out["tokens"]) == 6
            # direct submit with the same seed answers identically
            h = router.submit([5, 9, 3], max_new_tokens=6,
                              temperature=0.7, seed=5)
            assert h.wait(timeout=30) and h.tokens == out["tokens"]
            # malformed sampling stays a structured 400
            conn.request("POST", "/generate", json.dumps(
                {"tokens": [1], "temperature": -1.0}),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 400
            resp.read()
            conn.close()
        finally:
            srv.shutdown()
            srv.server_close()
            router.close()
