"""Convergence-at-scale harness (ISSUE 20): cell vocabulary/legality,
the per-cell tolerance table, harness determinism, rejected-cell
fail-fast, the matrix verdict contract, the bench_zoo converge rows
(satellite 1), and the HOROVOD_CONVERGE_* knob validation."""
import numpy as np
import pytest

from horovod_tpu.converge import (ADASUM_REFERENCE, Cell, REFERENCE,
                                  REJECTED, RUNNABLE, SKIPPED, Tolerance,
                                  all_cells, cell_status, tolerance_for)


# -- matrix vocabulary + legality (pure, no hvd state) ---------------------

class TestMatrix:
    def test_all_cells_is_the_full_product(self):
        cells = all_cells()
        assert len(cells) == 36 and len(set(cells)) == 36
        assert REFERENCE in cells and ADASUM_REFERENCE in cells
        assert Cell("int8", "adasum", "direct") in cells
        assert Cell("bf16", "avg", "rs_ag").name == "bf16xavgxrs_ag"

    def test_cell_status_legality(self):
        # rejected-by-design rows, with the substring the raise carries
        st, detail = cell_status(Cell("none", "adasum", "rs_ag"), 8)
        assert st == REJECTED and detail == "applies to Sum/Average only"
        st, detail = cell_status(Cell("int8", "sum", "rhd"), 8)
        assert st == REJECTED and detail == "conflict"
        # adasum+algo rejection wins over int8+algo (same precedence as
        # the engine's _check_allreduce_request)
        st, detail = cell_status(Cell("int8", "adasum", "rs_ag"), 8)
        assert st == REJECTED and detail == "applies to Sum/Average only"
        # topology-illegal algos are SKIPPED, never silently run
        st, _ = cell_status(Cell("none", "sum", "rhd"), 6)
        assert st == SKIPPED
        st, _ = cell_status(Cell("none", "sum", "two_level"), 8, None)
        assert st == SKIPPED
        st, _ = cell_status(Cell("none", "sum", "two_level"), 8, (4, 2))
        assert st == RUNNABLE
        # the tentpole row: int8 x adasum x direct RUNS (PR 1 lifted)
        st, _ = cell_status(Cell("int8", "adasum", "direct"), 8)
        assert st == RUNNABLE
        with pytest.raises(ValueError, match="unknown wire format"):
            cell_status(Cell("fp4", "sum", "direct"), 8)
        with pytest.raises(ValueError, match="unknown op"):
            cell_status(Cell("none", "min", "direct"), 8)
        with pytest.raises(ValueError, match="unknown algorithm"):
            cell_status(Cell("none", "sum", "ring3"), 8)

    def test_tolerance_table_covers_every_cell(self):
        for cell in all_cells():
            tol = tolerance_for(cell)
            assert isinstance(tol, Tolerance)
            assert tol.baseline in ("reference", "adasum_reference")
            assert 0 < tol.final_rel <= 1 and 0 < tol.area_rel <= 1
            assert 0 < tol.converge_frac < 1
            if cell.op == "adasum":
                # adasum cells judge against the adasum baseline (it is
                # a different optimizer) — except the baseline itself
                expected = ("reference" if cell.fmt == "none"
                            else "adasum_reference")
                assert tol.baseline == expected
        # the PR 1 EF bar, verbatim: int8 within 2% of same-op fp32
        assert tolerance_for(Cell("int8", "adasum", "direct")).final_rel \
            == 0.02
        assert tolerance_for(Cell("int8", "sum", "direct")).final_rel \
            == 0.02

    def test_measured_model_overrides(self):
        # resnet18's chaotic quantized-Adasum rows carry the measured
        # bound; an unknown model falls back to the generic table
        quant = Cell("int8", "adasum", "direct")
        assert tolerance_for(quant, "resnet18").final_rel == 0.60
        assert tolerance_for(quant, "gpt_tiny").final_rel == 0.02
        assert tolerance_for(quant).final_rel == 0.02
        # resnet18's int8 sum family carries the measured 6% band; the
        # exact cells and every other model keep the generic table
        assert tolerance_for(Cell("int8", "sum", "direct"),
                             "resnet18").final_rel == 0.06
        assert tolerance_for(Cell("int8", "sum", "direct"),
                             "gpt_tiny").final_rel == 0.02
        assert tolerance_for(Cell("none", "sum", "direct"),
                             "resnet18").final_rel == 0.02
        assert tolerance_for(Cell("none", "adasum", "direct"),
                             "resnet18").baseline == "reference"


# -- bench_zoo converge rows (satellite 1) ---------------------------------

class TestConvergeZoo:
    def test_rows_and_unknown_model(self):
        from horovod_tpu.models.bench_zoo import (CONVERGE_MODELS,
                                                  build_converge_model)
        assert set(CONVERGE_MODELS) == {"resnet18", "gpt_tiny", "moe_tiny"}
        with pytest.raises(ValueError, match="unknown converge model"):
            build_converge_model("resnet50", nranks=2)

    @pytest.mark.parametrize("model", ["gpt_tiny", "moe_tiny"])
    def test_model_is_seeded_and_differentiable(self, model):
        import jax
        from horovod_tpu.models.bench_zoo import build_converge_model
        loss_fn, params, batch_fn = build_converge_model(
            model, nranks=2, batch_size=2, seed=0)
        loss_fn2, params2, batch_fn2 = build_converge_model(
            model, nranks=2, batch_size=2, seed=0)
        # same seed => same init and same data
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(params)[0]),
            np.asarray(jax.tree_util.tree_leaves(params2)[0]))
        b = batch_fn(0)
        np.testing.assert_array_equal(np.asarray(batch_fn(2)),
                                      np.asarray(b))    # pool of 2 repeats
        my = jax.tree_util.tree_map(lambda a: a[0], b)
        g = jax.grad(loss_fn)(params, my)
        assert any(float(np.abs(np.asarray(x)).max()) > 0
                   for x in jax.tree_util.tree_leaves(g))


# -- harness ---------------------------------------------------------------

class TestHarness:
    def test_run_cell_deterministic(self, hvd):
        from horovod_tpu.converge.harness import run_cell
        a = run_cell("gpt_tiny", REFERENCE, steps=3, lr=0.1)
        b = run_cell("gpt_tiny", REFERENCE, steps=3, lr=0.1)
        assert a["curve"] == b["curve"]          # bit-identical replay
        assert len(a["curve"]) == 4
        assert a["final"] < a["initial"]         # it optimizes
        assert a["rank_coherence"] <= 1e-3

    def test_int8_adasum_cell_tracks_its_baseline(self, hvd):
        """The tentpole end-to-end: the lifted int8 x Adasum cell holds
        the PR 1 EF bar against fp32 Adasum inside the harness."""
        from horovod_tpu.converge.harness import run_cell
        base = run_cell("gpt_tiny", ADASUM_REFERENCE, steps=5, lr=0.1)
        quant = run_cell("gpt_tiny", Cell("int8", "adasum", "direct"),
                         steps=5, lr=0.1)
        rel = abs(quant["final"] - base["final"]) / abs(base["final"])
        assert rel <= 0.02, (base["final"], quant["final"])

    def test_rejected_cell_fails_fast_through_real_enqueue(self, hvd):
        from horovod_tpu.converge.harness import check_rejection
        cell = Cell("none", "adasum", "rs_ag")
        _, detail = cell_status(cell, hvd.size())
        entry = check_rejection(cell, detail)
        assert entry["status"] == "rejected" and entry["error_ok"]
        # a wrong expectation is NOT error_ok (the harness cannot be
        # satisfied by any raise — the message must match)
        entry = check_rejection(cell, "some other message")
        assert not entry["error_ok"]

    def test_run_matrix_verdict_contract(self, hvd):
        from horovod_tpu.converge.harness import run_matrix
        cells = [REFERENCE, ADASUM_REFERENCE,
                 Cell("int8", "adasum", "direct"),
                 Cell("none", "adasum", "rs_ag"),     # rejected
                 Cell("none", "sum", "rhd")]          # runnable on np8
        v = run_matrix(["gpt_tiny"], steps=6, lr=0.5, cells=cells)
        cells_out = v["models"]["gpt_tiny"]
        assert set(cells_out) == {c.name for c in cells}
        assert v["world"] == hvd.size()
        ref = cells_out[REFERENCE.name]
        assert ref["status"] == "ran" and ref["pass"]
        assert ref["final_rel"] == 0.0               # its own baseline
        rej = cells_out["nonexadasumxrs_ag"]
        assert rej["status"] == "rejected" and rej["error_ok"]
        quant = cells_out["int8xadasumxdirect"]
        assert quant["baseline"] == "adasum_reference"
        assert quant["pass"], quant
        assert v["ok"] is True
        # unknown model fails fast (harness misuse, not a verdict)
        with pytest.raises(ValueError, match="unknown converge model"):
            run_matrix(["resnet50"], cells=[REFERENCE])

    def test_matrix_metrics_instrumented(self, hvd):
        from horovod_tpu import obs
        from horovod_tpu.converge.harness import run_matrix
        run_matrix(["gpt_tiny"], steps=2, lr=0.1,
                   cells=[REFERENCE, Cell("none", "adasum", "rs_ag")])
        R = obs.get_registry()
        ran = R.get("hvd_converge_cells_total", {"status": "ran"})
        rej = R.get("hvd_converge_cells_total", {"status": "rejected"})
        assert ran is not None and ran.value >= 1
        assert rej is not None and rej.value >= 1
        g = R.get("hvd_converge_final_loss",
                  {"model": "gpt_tiny", "cell": REFERENCE.name})
        assert g is not None and g.value > 0
        d = R.get("hvd_converge_delta_rel",
                  {"model": "gpt_tiny", "cell": REFERENCE.name})
        assert d is not None and d.value == 0.0


# -- knob plumbing ---------------------------------------------------------

class TestConvergeKnobs:
    def test_defaults_and_env_parse(self, monkeypatch):
        from horovod_tpu.core.config import Config
        cfg = Config()
        assert (cfg.converge_steps, cfg.converge_batch,
                cfg.converge_seed) == (30, 4, 0)
        assert cfg.converge_lr == 0.0 and cfg.converge_tol_scale == 1.0
        assert cfg.converge_models == "resnet18,gpt_tiny"
        from horovod_tpu.models.bench_zoo import (CONVERGE_LRS,
                                                  CONVERGE_MODELS)
        assert set(CONVERGE_LRS) == set(CONVERGE_MODELS)
        monkeypatch.setenv("HOROVOD_CONVERGE_STEPS", "7")
        monkeypatch.setenv("HOROVOD_CONVERGE_LR", "0.05")
        monkeypatch.setenv("HOROVOD_CONVERGE_MODELS", "moe_tiny")
        cfg = Config.from_env()
        assert cfg.converge_steps == 7 and cfg.converge_lr == 0.05
        assert cfg.converge_models == "moe_tiny"

    def test_strict_parse_and_validation(self, monkeypatch):
        from horovod_tpu.core.config import Config
        monkeypatch.setenv("HOROVOD_CONVERGE_STEPS", "many")
        with pytest.raises(ValueError, match="HOROVOD_CONVERGE_STEPS"):
            Config.from_env()
        monkeypatch.delenv("HOROVOD_CONVERGE_STEPS")
        for field, bad in [("converge_steps", 0), ("converge_batch", 0),
                           ("converge_seed", -1), ("converge_lr", -0.1),
                           ("converge_models", ""),
                           ("converge_tol_scale", 0.0)]:
            cfg = Config(**{field: bad})
            with pytest.raises(ValueError, match="HOROVOD_CONVERGE_"):
                cfg.validate()


# -- multi-process evaluate() core (log -> verdict, no processes) ----------

class TestProcEvaluate:
    def _write(self, tmp_path, rank, losses):
        import json
        with open(tmp_path / f"events.{rank}.jsonl", "w") as f:
            for i, v in enumerate(losses):
                f.write(json.dumps({"kind": "loss", "step": i,
                                    "loss": v}) + "\n")

    def test_verdict_on_synthetic_logs(self, tmp_path):
        from horovod_tpu.converge.proc import evaluate
        good = [1.0, 0.8, 0.6]
        for r in range(2):
            self._write(tmp_path, r, good)
        v = evaluate(str(tmp_path), np_=2, steps=2, converge_frac=0.95)
        assert v["curves_complete"] and v["curves_identical"]
        assert v["descended"] and v["max_curve_spread"] == 0.0

    def test_verdict_catches_divergent_and_missing_ranks(self, tmp_path):
        from horovod_tpu.converge.proc import evaluate
        self._write(tmp_path, 0, [1.0, 0.8, 0.6])
        v = evaluate(str(tmp_path), np_=2, steps=2, converge_frac=0.95)
        assert not v["curves_complete"]
        self._write(tmp_path, 1, [1.0, 0.8, 0.7])   # rank 1 diverged
        v = evaluate(str(tmp_path), np_=2, steps=2, converge_frac=0.95)
        assert v["curves_complete"] and not v["curves_identical"]
        self._write(tmp_path, 1, [1.0, 0.8, 0.6])
        v = evaluate(str(tmp_path), np_=2, steps=2, converge_frac=0.5)
        assert v["curves_identical"] and not v["descended"]
