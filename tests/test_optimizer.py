"""DistributedOptimizer + compression + state-sync function tests.

Mirrors test/parallel/test_torch.py optimizer/compression sections and
tensorflow broadcast_variables tests."""
import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp


def _stacked_grads(n, seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(n, 4, 3).astype(np.float32),
            "b": rng.randn(n, 3).astype(np.float32)}


def test_distributed_optimizer_averages(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0))
    grads = _stacked_grads(8)
    params = {"w": jnp.zeros((8, 4, 3)), "b": jnp.zeros((8, 3))}
    state = opt.init(params)
    updates, state = opt.update(grads, state, params)
    np.testing.assert_allclose(
        np.asarray(updates["w"]),
        np.tile(-grads["w"].mean(0), (8, 1, 1)), rtol=1e-5)


def test_distributed_optimizer_sum_op(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), op=hvd.Sum)
    grads = _stacked_grads(8)
    params = jax.tree_util.tree_map(jnp.zeros_like, grads)
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    np.testing.assert_allclose(
        np.asarray(updates["b"]), np.tile(-grads["b"].sum(0), (8, 1)),
        rtol=1e-4)


def test_gradient_predivide_factor(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), gradient_predivide_factor=2.0)
    grads = _stacked_grads(8)
    params = jax.tree_util.tree_map(jnp.zeros_like, grads)
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    # prescale 1/2, sum, postscale 2 -> with Average's /n folded the result
    # still equals the plain mean (reference: torch/optimizer.py:199-204)
    np.testing.assert_allclose(
        np.asarray(updates["w"]), np.tile(-grads["w"].mean(0), (8, 1, 1)),
        rtol=1e-4)


def test_predivide_requires_average(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    with pytest.raises(ValueError, match="Average"):
        DistributedOptimizer(optax.sgd(1.0), op=hvd.Sum,
                             gradient_predivide_factor=2.0)


def test_fp16_compression(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), compression=hvd.Compression.fp16)
    grads = _stacked_grads(8)
    params = jax.tree_util.tree_map(jnp.zeros_like, grads)
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    assert updates["w"].dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(
        np.asarray(updates["w"]), np.tile(-grads["w"].mean(0), (8, 1, 1)),
        rtol=5e-2, atol=2e-3)


def test_backward_passes_per_step(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    g1 = _stacked_grads(8, seed=1)
    g2 = _stacked_grads(8, seed=2)
    params = jax.tree_util.tree_map(jnp.zeros_like, g1)
    state = opt.init(params)
    u1, state = opt.update(g1, state, params)
    # first micro-step: no apply yet
    assert float(jnp.abs(u1["w"]).max()) == 0.0
    u2, state = opt.update(g2, state, params)
    expect = -(g1["w"] + g2["w"]).mean(0) / 2.0
    np.testing.assert_allclose(np.asarray(u2["w"]),
                               np.tile(expect, (8, 1, 1)), rtol=1e-5)


def test_adasum_op(hvd):
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), op=hvd.Adasum)
    grads = {"w": np.tile(np.linspace(-1, 1, 6, dtype=np.float32), (8, 1))}
    params = {"w": jnp.zeros((8, 6))}
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    # identical rows -> adasum returns the row
    np.testing.assert_allclose(np.asarray(updates["w"]), -grads["w"],
                               rtol=1e-5)


def test_local_vars_skip_allreduce_eager(hvd):
    """local_vars gradients pass through unreduced (reference:
    register_local_var, horovod/_keras/__init__.py:97)."""
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(1.0), local_vars=["b"])
    grads = _stacked_grads(8)
    params = jax.tree_util.tree_map(jnp.zeros_like, grads)
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    np.testing.assert_allclose(
        np.asarray(updates["w"]),
        np.tile(-grads["w"].mean(0), (8, 1, 1)), rtol=1e-5)
    # "b" kept its per-rank rows: no averaging happened
    np.testing.assert_allclose(np.asarray(updates["b"]), -grads["b"],
                               rtol=1e-5)


def test_local_vars_predicate_form(hvd):
    from horovod_tpu.optim.optimizer import allreduce_gradients
    grads = _stacked_grads(8)
    out = allreduce_gradients(
        grads, local_vars=lambda path, leaf: leaf.ndim == 2)  # matches "b"
    np.testing.assert_allclose(np.asarray(out["b"]), grads["b"], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out["w"]), np.tile(grads["w"].mean(0), (8, 1, 1)),
        rtol=1e-5)


def test_partial_distributed_gradient_tape_ingraph(hvd):
    """PartialDistributedGradientTape under shard_map: the local leaf keeps
    its per-device gradient while the shared leaf is averaged
    (reference: tensorflow/__init__.py:1189)."""
    from horovod_tpu.optim.optimizer import PartialDistributedGradientTape
    from jax.sharding import PartitionSpec as P
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("hvd",))

    def loss(p, x):
        return jnp.sum(p["shared"] * x) + jnp.sum(p["local_head"] * x * x)

    g = PartialDistributedGradientTape(loss, local_vars=["local_head"],
                                       axis_name="hvd")

    def step(p, x):
        return g({"shared": p["shared"][0], "local_head": p["local_head"][0]},
                 x[0])

    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 1, 4)
    params = {"shared": jnp.ones((8, 1, 4)), "local_head": jnp.ones((8, 1, 4))}
    f = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P("hvd"), P("hvd")),
        out_specs={"shared": P("hvd"), "local_head": P("hvd")}))
    out = f(params, jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(out["shared"]).reshape(8, 4),
        np.tile(x.reshape(8, 4).mean(0), (8, 1)), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["local_head"]).reshape(8, 4),
        (x * x).reshape(8, 4), rtol=1e-5)


def test_ingraph_mode_under_shard_map(hvd):
    """The performance path: optimizer used inside shard_map with axis_name."""
    from horovod_tpu.optim.optimizer import DistributedOptimizer
    from jax.sharding import PartitionSpec as P
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("hvd",))
    opt = DistributedOptimizer(optax.sgd(0.1), axis_name="hvd")
    grads = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    params = jnp.zeros((8, 4))

    def step(p, g):  # per-device block [1, 4]
        state = opt.init(p)
        updates, _ = opt.update(g, state, p)
        return updates

    f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=(P("hvd"), P("hvd")),
                              out_specs=P("hvd")))
    out = np.asarray(f(params, jnp.asarray(grads)))
    np.testing.assert_allclose(out, np.tile(-0.1 * grads.mean(0), (8, 1)),
                               rtol=1e-5)


def test_broadcast_parameters(hvd):
    from horovod_tpu.optim.functions import broadcast_parameters
    stacked = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    tree = {"stacked": stacked, "replicated": np.ones((4,), np.float32)}
    out = broadcast_parameters(tree, root_rank=2)
    np.testing.assert_array_equal(np.asarray(out["stacked"]),
                                  np.tile(stacked[2], (8, 1)))
    np.testing.assert_array_equal(np.asarray(out["replicated"]), np.ones(4))


def test_broadcast_object(hvd):
    from horovod_tpu.optim.functions import broadcast_object
    obj = {"epoch": 3, "names": ["a", "b"]}
    assert broadcast_object(obj) == obj


def test_allgather_object(hvd):
    from horovod_tpu.optim.functions import allgather_object
    objs = allgather_object({"r": 1})
    assert len(objs) == 8 and all(o == {"r": 1} for o in objs)
    per_rank = allgather_object([{"r": i} for i in range(8)])
    assert per_rank[5] == {"r": 5}


def test_spar_compressor_unbiased_shape(hvd):
    from horovod_tpu.optim.compression import SparCompressor
    x = jnp.ones((8, 100))
    c, ctx = SparCompressor.compress(x)
    assert c.shape == x.shape
    kept = float((np.asarray(c) != 0).mean())
    assert 0.1 < kept < 0.5  # ~30% kept


class TestDistributedGrad:
    """DistributedGradientTape analog (tensorflow/__init__.py:1026-1110)."""

    def test_eager_stacked_grad_averaged(self, hvd):
        n = hvd.size()

        def loss(w):                      # w: stacked [n, d]
            return (w ** 2).sum()

        g = hvd.distributed_grad(loss)
        w = np.tile(np.arange(1.0, 4.0, dtype=np.float32), (n, 1))
        w = w * (1 + np.arange(n, dtype=np.float32))[:, None]  # per-rank rows
        out = np.asarray(g(jnp.asarray(w)))
        # grad rows = 2*w rows, averaged across ranks
        expect = np.tile((2 * w).mean(axis=0), (n, 1))
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_eager_has_aux_and_pytree(self, hvd):
        n = hvd.size()

        def loss(params):
            l = (params["a"] ** 2).sum() + (params["b"] ** 2).sum()
            return l, {"l": l}

        g = hvd.distributed_grad(loss, has_aux=True)
        params = {"a": np.ones((n, 2), np.float32),
                  "b": 2 * np.ones((n, 3), np.float32)}
        grads, aux = g(jax.tree_util.tree_map(jnp.asarray, params))
        np.testing.assert_allclose(np.asarray(grads["a"]),
                                   2 * np.ones((n, 2)), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["b"]),
                                   4 * np.ones((n, 3)), rtol=1e-6)
        assert "l" in aux

    def test_ingraph_grad_inside_shard_map(self, hvd):
        from jax.sharding import PartitionSpec as P
        n = hvd.size()
        mesh = hvd.core.basics.get_mesh()

        def local(w, x):                  # per-device shard
            def loss(w):
                return ((x @ w) ** 2).sum()
            return hvd.distributed_grad(loss, axis_name="hvd")(w)

        f = jax.jit(jax.shard_map(
            local, mesh=mesh, in_specs=(P(), P("hvd")), out_specs=P()))
        w = jnp.ones((3, 2), jnp.float32)
        x = jnp.asarray(np.random.RandomState(0).rand(2 * n, 3)
                        .astype(np.float32))
        out = np.asarray(f(w, x))
        # compare against global-batch gradient / n... pmean averages the
        # per-shard SUM gradients, so expectation = mean over shards
        shards = np.split(np.asarray(x), n)
        per = [2 * s.T @ (s @ np.asarray(w)) for s in shards]
        np.testing.assert_allclose(out, np.mean(per, axis=0), rtol=1e-5)

    def test_alias_and_validation(self, hvd):
        assert hvd.DistributedGradientTape is hvd.distributed_grad
        with pytest.raises(ValueError, match="requires op=Average"):
            hvd.allreduce_gradients(
                jnp.ones((hvd.size(), 2)), op=hvd.Sum,
                gradient_predivide_factor=2.0)


class TestHierarchicalAdasum:
    """Two-level Adasum over a (cross, local) hier mesh — the
    AdasumGpuAllreduceOp::NcclHierarchical analog
    (adasum_gpu_operations.cc:135-138: local sum reduce-scatter, cross
    Adasum per chunk, local allgather)."""

    @staticmethod
    def _hier_2x4(hvd):
        from horovod_tpu.core.mesh import build_hierarchical_mesh
        st = hvd.core.basics.get_state()
        prev = st.hier_mesh
        st.hier_mesh = build_hierarchical_mesh(jax.devices(), local_size=4)
        return prev

    @staticmethod
    def _expected(x, cross, local):
        """Host model of the two-level algorithm: per-node sum, then
        per-chunk pairwise Adasum across nodes (chunk = this local rank's
        reduce-scatter share of the padded flat buffer)."""
        from horovod_tpu.ops.adasum import adasum_combine
        n, flatdim = x.shape[0], int(np.prod(x.shape[1:]))
        pad = (-flatdim) % local
        flat = np.concatenate(
            [x.reshape(n, -1), np.zeros((n, pad), x.dtype)], axis=1)
        nodes = flat.reshape(cross, local, -1).sum(axis=1)  # [cross, L]
        chunks = np.split(nodes, local, axis=1)             # per local rank
        combined = [
            np.asarray(adasum_combine(jnp.asarray(c[0]), jnp.asarray(c[1])))
            for c in chunks
        ]
        out = np.concatenate(combined)[:flatdim]
        return np.tile(out.reshape(x.shape[1:])[None], (n,) + (1,) * (x.ndim - 1))

    def test_two_level_matches_host_model(self, hvd):
        from horovod_tpu.ops.adasum import adasum_allreduce
        prev = self._hier_2x4(hvd)
        try:
            rng = np.random.RandomState(5)
            x = rng.randn(8, 5).astype(np.float32)   # flat 5 -> padded to 8
            out = np.asarray(adasum_allreduce(jnp.asarray(x),
                                              hierarchical=True))
            np.testing.assert_allclose(out, self._expected(x, 2, 4),
                                       rtol=1e-4)
        finally:
            hvd.core.basics.get_state().hier_mesh = prev

    def test_scale_invariance_both_levels(self, hvd):
        # combine(c*a, c*b) == c*combine(a, b) holds per chunk, and the
        # local sum is linear, so the whole two-level op is
        # scale-equivariant: hier_adasum(c*X) == c * hier_adasum(X).
        from horovod_tpu.ops.adasum import adasum_allreduce
        prev = self._hier_2x4(hvd)
        try:
            rng = np.random.RandomState(7)
            x = rng.randn(8, 6).astype(np.float32)
            base = np.asarray(adasum_allreduce(jnp.asarray(x),
                                               hierarchical=True))
            scaled = np.asarray(adasum_allreduce(jnp.asarray(4.0 * x),
                                                 hierarchical=True))
            np.testing.assert_allclose(scaled, 4.0 * base, rtol=1e-4)
            # identical node contributions -> result equals the node sum
            # (combine(g, g) == g at the cross level)
            y = np.tile(x[:4][None], (2, 1, 1)).reshape(8, 6)
            out = np.asarray(adasum_allreduce(jnp.asarray(y),
                                              hierarchical=True))
            np.testing.assert_allclose(out, np.tile(x[:4].sum(0), (8, 1)),
                                       rtol=1e-4)
        finally:
            hvd.core.basics.get_state().hier_mesh = prev

    def test_validation(self, hvd):
        from horovod_tpu.ops.adasum import adasum_allreduce
        ps = hvd.add_process_set([0, 1])
        with pytest.raises(ValueError, match="global process set"):
            adasum_allreduce(np.ones((2, 3), np.float32), process_set=ps,
                             hierarchical=True)
        hvd.remove_process_set(ps)

    def test_env_flag_selects_hierarchical(self, hvd):
        # HOROVOD_ADASUM_HIERARCHICAL makes hvd.allreduce(op=Adasum) take
        # the two-level path on the global set
        from horovod_tpu.ops.adasum import adasum_allreduce
        prev = self._hier_2x4(hvd)
        cfg = hvd.core.basics.get_config()
        try:
            cfg.adasum_hierarchical = True
            rng = np.random.RandomState(9)
            x = rng.randn(8, 4).astype(np.float32)
            out = np.asarray(hvd.allreduce(jnp.asarray(x), hvd.Adasum))
            np.testing.assert_allclose(out, self._expected(x, 2, 4),
                                       rtol=1e-4)
        finally:
            cfg.adasum_hierarchical = False
            hvd.core.basics.get_state().hier_mesh = prev
