"""Checkpoint subsystem tests: orbax save/restore round-trips, rank-0
convention, latest-step selection, FileBackedState disk commits.

The reference has no checkpoint code of its own (SURVEY §5.4); these tests
cover the TPU-native subsystem that replaces its three conventions."""
import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.checkpoint import (Checkpointer, FileBackedState,
                                    latest_step, restore_checkpoint,
                                    save_checkpoint)


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                "b": np.float32(1.5), "step": 7}
        with Checkpointer(str(tmp_path), async_save=False) as ckpt:
            ckpt.save(7, tree)
            out = ckpt.restore()
        np.testing.assert_array_equal(out["w"], tree["w"])
        assert out["step"] == 7

    def test_jax_arrays_roundtrip(self, hvd, tmp_path):
        tree = {"p": jnp.ones((4, 4)) * 3.0}
        with Checkpointer(str(tmp_path), async_save=False) as ckpt:
            ckpt.save(0, tree)
            out = ckpt.restore(0)
        np.testing.assert_allclose(np.asarray(out["p"]), 3.0)

    def test_latest_step_and_retention(self, tmp_path):
        with Checkpointer(str(tmp_path), max_to_keep=2,
                          async_save=False) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, {"x": np.full(2, float(s))})
            ckpt.wait_until_finished()
            assert ckpt.latest_step() == 3
            assert ckpt.all_steps() == [2, 3]
            out = ckpt.restore()  # latest
        np.testing.assert_array_equal(out["x"], [3.0, 3.0])

    def test_restore_with_target_structure(self, tmp_path):
        tree = {"a": np.ones(3, np.float32), "n": 4}
        with Checkpointer(str(tmp_path), async_save=False) as ckpt:
            ckpt.save(0, tree)
            out = ckpt.restore(0, target={"a": np.zeros(3, np.float32),
                                          "n": 0})
        np.testing.assert_array_equal(out["a"], tree["a"])
        assert out["n"] == 4

    def test_restore_missing_raises(self, tmp_path):
        with Checkpointer(str(tmp_path), async_save=False) as ckpt:
            with pytest.raises(FileNotFoundError):
                ckpt.restore()

    def test_async_save_waits(self, tmp_path):
        with Checkpointer(str(tmp_path), async_save=True) as ckpt:
            ckpt.save(0, {"x": np.arange(1000, dtype=np.float32)})
            ckpt.wait_until_finished()
            out = ckpt.restore(0)
        assert out["x"].shape == (1000,)


class TestConveniences:
    def test_one_call_roundtrip(self, tmp_path):
        save_checkpoint(str(tmp_path), {"k": np.eye(2)}, step=5)
        assert latest_step(str(tmp_path)) == 5
        out = restore_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(out["k"], np.eye(2))


class TestFileBackedState:
    def test_commit_persists_and_reloads(self, hvd, tmp_path):
        s = FileBackedState(str(tmp_path), async_save=False,
                            step=0, w=np.zeros(3))
        s.step = 3
        s.w = np.full(3, 7.0)
        s.commit()
        s.close()

        # fresh state object, as after a full job restart
        s2 = FileBackedState(str(tmp_path), async_save=False,
                             step=0, w=np.zeros(3))
        assert s2.load_latest()
        assert int(s2.step) == 3
        np.testing.assert_array_equal(np.asarray(s2.w), np.full(3, 7.0))
        # restore() rolls back to the loaded commit, not the ctor values
        s2.w = np.zeros(3)
        s2.restore()
        np.testing.assert_array_equal(np.asarray(s2.w), np.full(3, 7.0))
        s2.close()

    def test_load_latest_empty_returns_false(self, hvd, tmp_path):
        # construction alone (in-memory initial commit) writes nothing
        s = FileBackedState(str(tmp_path), async_save=False, x=1)
        assert s.load_latest() is False
        s.close()


class TestReshardOnRestore:
    def test_fsdp_checkpoint_restores_to_new_layout(self, hvd, tmp_path):
        """Save FSDP-sharded training state, restore it re-placed under a
        different sharding layout (elastic topology change), training
        continues with identical values."""
        import optax
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.checkpoint import (restore_checkpoint,
                                            save_checkpoint)
        from horovod_tpu.models.llama import (Llama,
                                              llama_partition_rules)
        from horovod_tpu.parallel.fsdp import FSDPRules
        from horovod_tpu.parallel.mesh_utils import make_mesh
        from horovod_tpu.parallel.tp import PartitionRules, shard_params
        from horovod_tpu.training import make_gspmd_train_step
        from tests.test_llama import _tiny
        import jax
        import jax.numpy as jnp

        mesh_a = make_mesh(dp=4, tp=2)
        cfg = _tiny()
        model = Llama(cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32)
        tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 1))
        rules_a = FSDPRules(llama_partition_rules(), mesh_a,
                            min_size=2 ** 8)
        tx = optax.adam(1e-2)
        params = shard_params(
            model.init(jax.random.PRNGKey(0), toks)["params"],
            mesh_a, rules_a)
        opt = tx.init(params)
        step_a = make_gspmd_train_step(model.apply, tx, mesh_a, rules_a,
                                       batch_spec=P("dp", None))
        params, opt, _ = step_a(params, opt, toks, tgts)
        save_checkpoint(str(tmp_path), {"params": params}, step=1)

        # new layout: pure dp, no fsdp/tp — the elastic-restart case
        mesh_b = make_mesh(dp=8)
        rules_b = PartitionRules([])
        restored = restore_checkpoint(str(tmp_path))["params"]
        params_b = shard_params(restored, mesh_b, rules_b)
        opt_b = tx.init(params_b)
        step_b = make_gspmd_train_step(model.apply, tx, mesh_b, rules_b,
                                       batch_spec=P("dp", None))
        _, _, loss_b = step_b(params_b, opt_b, toks, tgts)

        # oracle: same two steps with never-sharded params
        params_c = shard_params(
            model.init(jax.random.PRNGKey(0), toks)["params"],
            mesh_b, rules_b)
        opt_c = tx.init(params_c)
        params_c, opt_c, _ = step_b(params_c, opt_c, toks, tgts)
        opt_c = tx.init(params_c)   # restart resets optimizer state too
        _, _, loss_c = step_b(params_c, opt_c, toks, tgts)
        np.testing.assert_allclose(float(loss_b), float(loss_c), rtol=1e-4)
