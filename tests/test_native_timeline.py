"""Native timeline writer tests (csrc/timeline.cc + timeline.py wiring)."""
import json
import os

import pytest

from horovod_tpu import native
from horovod_tpu.timeline import Timeline

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_native_writer_valid_json(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.start()
    assert tl._native is not None, "native writer should be selected"
    for i in range(100):
        tl.begin(f"tensor_{i % 7}", "ALLREDUCE")
        tl.end(f"tensor_{i % 7}", "ALLREDUCE")
    tl.instant("CYCLE", {"n": 3})
    tl.stop()
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert len(evs) == 201
    assert evs[0]["name"] == "ALLREDUCE"
    assert evs[0]["ph"] == "B"
    assert evs[0]["args"] == {"tensor": "tensor_0"}
    assert evs[-1]["name"] == "CYCLE"
    assert evs[-1]["args"] == {"n": 3}


def test_native_writer_escaping(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.start()
    tl.begin('weird"name\\with\nstuff', "PH")
    tl.stop()
    with open(path) as f:
        doc = json.load(f)
    assert doc["traceEvents"][0]["args"]["tensor"] == \
        'weird"name\\with\nstuff'


def test_python_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_TIMELINE_NATIVE", "0")
    path = str(tmp_path / "tl.json")
    tl = Timeline(path)
    tl.start()
    assert tl._native is None
    tl.begin("t", "X")
    tl.end("t", "X")
    tl.stop()
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == 2


def test_mark_cycles(tmp_path):
    path = str(tmp_path / "tl.json")
    tl = Timeline(path, mark_cycles=True)
    tl.start()
    tl.mark_cycle()
    tl.stop()
    with open(path) as f:
        doc = json.load(f)
    assert doc["traceEvents"][0]["name"] == "CYCLE"
    assert os.path.getsize(path) > 0
