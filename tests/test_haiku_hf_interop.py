"""Framework-binding tests: dm-haiku and HF transformers (Flax) front ends.

The reference's per-framework binding tests live in test/parallel/
test_torch.py / test_tensorflow.py etc.; these cover the JAX-ecosystem
equivalents (flax is native, haiku + HF are bindings)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.training import init_replicated, shard_batch

hk = pytest.importorskip("haiku")


def _xy(n=16, d=8, classes=4, seed=0):
    r = np.random.RandomState(seed)
    x = r.randn(n, d).astype(np.float32)
    y = r.randint(0, classes, (n,)).astype(np.int32)
    return x, y


def _ce(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


class TestHaiku:
    def test_train_step_learns(self, hvd):
        import horovod_tpu.interop.haiku as hvd_hk
        mesh = hvd.core.basics.get_mesh()
        net = hk.transform(lambda x: hk.nets.MLP([32, 4])(x))
        x, y = _xy()
        rng = jax.random.PRNGKey(0)
        params = init_replicated(net.init(rng, jnp.asarray(x[:1])), mesh)
        step = hvd_hk.make_train_step(net, optax.adam(1e-2), mesh,
                                      loss_fn=_ce)
        opt = init_replicated(step.init_opt_state(params), mesh)
        xi, yi = shard_batch(x, mesh), shard_batch(y, mesh)
        params, opt, l1 = step(params, opt, rng, xi, yi)
        for _ in range(5):
            params, opt, l2 = step(params, opt, rng, xi, yi)
        assert float(l2) < float(l1)

    def test_train_step_with_state_syncs(self, hvd):
        """hk state (e.g. BN averages) must come back pmean-synced."""
        import horovod_tpu.interop.haiku as hvd_hk
        mesh = hvd.core.basics.get_mesh()

        def fwd(x):
            # running mean of the batch — per-replica values differ, so a
            # correct implementation must pmean them (SyncBatchNorm)
            mean = hk.get_state("mean", [], jnp.float32, init=jnp.zeros)
            hk.set_state("mean", 0.9 * mean + 0.1 * x.mean())
            return hk.nets.MLP([16, 4])(x)

        net = hk.transform_with_state(fwd)
        x, y = _xy()
        rng = jax.random.PRNGKey(0)
        params, state = net.init(rng, jnp.asarray(x[:1]))
        params = init_replicated(params, mesh)
        state = init_replicated(state, mesh)
        step = hvd_hk.make_train_step(net, optax.adam(1e-2), mesh,
                                      loss_fn=_ce, has_state=True)
        opt = init_replicated(step.init_opt_state(params), mesh)
        xi, yi = shard_batch(x, mesh), shard_batch(y, mesh)
        params, state, opt, loss = step(params, state, opt, rng, xi, yi)
        # synced value == update computed from the global batch mean
        expect = 0.1 * x.mean()
        np.testing.assert_allclose(float(state["~"]["mean"]), expect,
                                   rtol=1e-5, atol=1e-6)
        assert np.isfinite(float(loss))

    def test_eval_step_averages_metric(self, hvd):
        import horovod_tpu.interop.haiku as hvd_hk
        mesh = hvd.core.basics.get_mesh()
        net = hk.transform(lambda x: hk.nets.MLP([8, 4])(x))
        x, y = _xy()
        rng = jax.random.PRNGKey(0)
        params = init_replicated(net.init(rng, jnp.asarray(x[:1])), mesh)

        def acc(out, labels):
            return jnp.mean((jnp.argmax(out, -1) == labels)
                            .astype(jnp.float32))

        ev = hvd_hk.make_eval_step(net, mesh, metric_fn=acc)
        val = ev(params, rng, shard_batch(x, mesh), shard_batch(y, mesh))
        assert 0.0 <= float(val) <= 1.0


class TestHF:
    @pytest.fixture()
    def tiny_bert(self):
        # function-scoped: train steps donate their param buffers, and on
        # CPU device_put may alias, so reusing one model across tests
        # would hand later tests deleted arrays
        transformers = pytest.importorskip("transformers")
        cfg = transformers.BertConfig(
            vocab_size=99, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=64, num_labels=3)
        model = transformers.FlaxBertForSequenceClassification(
            cfg, seed=0, dtype=jnp.float32)
        return model

    def _batch(self, n=16, seq=10, vocab=99, classes=3, seed=0):
        r = np.random.RandomState(seed)
        return {
            "input_ids": r.randint(0, vocab, (n, seq)).astype(np.int32),
            "attention_mask": np.ones((n, seq), np.int32),
            "labels": r.randint(0, classes, (n,)).astype(np.int32),
        }

    def test_finetune_step_learns(self, hvd, tiny_bert):
        import horovod_tpu.interop.hf as hvd_hf
        mesh = hvd.core.basics.get_mesh()
        model = tiny_bert
        step = hvd_hf.make_finetune_step(model, optax.adamw(1e-3), mesh)
        params = init_replicated(model.params, mesh)
        opt = init_replicated(step.init_opt_state(params), mesh)
        batch = {k: shard_batch(v, mesh)
                 for k, v in self._batch().items()}
        rng = jax.random.PRNGKey(0)
        params, opt, l1 = step(params, opt, rng, batch)
        for _ in range(3):
            params, opt, l2 = step(params, opt, rng, batch)
        assert float(l2) < float(l1)

    def test_eval_accuracy_bounds(self, hvd, tiny_bert):
        import horovod_tpu.interop.hf as hvd_hf
        mesh = hvd.core.basics.get_mesh()
        model = tiny_bert
        ev = hvd_hf.make_eval_step(model, mesh)
        params = init_replicated(model.params, mesh)
        batch = {k: shard_batch(v, mesh)
                 for k, v in self._batch(seed=1).items()}
        acc = ev(params, batch)
        assert 0.0 <= float(acc) <= 1.0

    def test_broadcast_parameters_reexport(self, hvd, tiny_bert):
        import horovod_tpu.interop.hf as hvd_hf
        out = hvd_hf.broadcast_parameters(tiny_bert.params, 0)
        l0 = jax.tree_util.tree_leaves(tiny_bert.params)[0]
        r0 = jax.tree_util.tree_leaves(out)[0]
        np.testing.assert_allclose(np.asarray(l0), np.asarray(r0))
