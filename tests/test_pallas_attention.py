"""Pallas flash-attention kernel tests (interpret mode on CPU; the same
kernel lowers to Mosaic on TPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_attention import flash_attention, fused_attention
from horovod_tpu.parallel.sp import attention_reference


def _rand(b, h, s, d, seed=0):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(b, h, s, d).astype(np.float32)),
            jnp.asarray(r.randn(b, h, s, d).astype(np.float32)),
            jnp.asarray(r.randn(b, h, s, d).astype(np.float32)))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = _rand(2, 3, 128, 16)
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_ragged_seq_causal():
    """Sq not divisible by block: padding path."""
    q, k, v = _rand(1, 2, 100, 8, seed=1)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_cross_attention_shapes():
    """Skv != Sq (non-causal requires divisible Skv)."""
    r = np.random.RandomState(2)
    q = jnp.asarray(r.randn(1, 2, 32, 8).astype(np.float32))
    k = jnp.asarray(r.randn(1, 2, 64, 8).astype(np.float32))
    v = jnp.asarray(r.randn(1, 2, 64, 8).astype(np.float32))
    ref = attention_reference(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=16, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_bf16_io():
    q, k, v = _rand(1, 2, 64, 16, seed=3)
    q, k, v = q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), \
        v.astype(jnp.bfloat16)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_fused_attention_dispatch():
    q, k, v = _rand(1, 1, 32, 8, seed=4)
    ref = fused_attention(q, k, v, force="reference")
    interp = fused_attention(q, k, v, force="interpret")
    np.testing.assert_allclose(np.asarray(interp), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_gpt_with_interpret_kernel(hvd):
    """GPT forward with the pallas kernel (interpret) matches reference."""
    from horovod_tpu.models.gpt import GPT, GPTConfig
    tokens = jnp.asarray(
        np.random.RandomState(5).randint(0, 64, (2, 32)), jnp.int32)
    cfg_ref = GPTConfig(vocab_size=64, num_layers=1, num_heads=2,
                        head_dim=8, max_seq_len=32, dtype=jnp.float32,
                        attention_impl="reference")
    cfg_pal = GPTConfig(vocab_size=64, num_layers=1, num_heads=2,
                        head_dim=8, max_seq_len=32, dtype=jnp.float32,
                        attention_impl="interpret")
    params = GPT(cfg_ref).init(jax.random.PRNGKey(0), tokens)["params"]
    out_ref = GPT(cfg_ref).apply({"params": params}, tokens)
    out_pal = GPT(cfg_pal).apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out_pal), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_padded_kv_shorter_than_q(causal):
    """Skv not divisible by block_k AND Skv < Sq: padded key positions must
    never receive softmax weight (regression: causal queries past Skv used
    to attend to the zero-padded keys)."""
    r = np.random.RandomState(3)
    q = jnp.asarray(r.randn(1, 2, 64, 8).astype(np.float32))
    k = jnp.asarray(r.randn(1, 2, 33, 8).astype(np.float32))
    v = jnp.asarray(r.randn(1, 2, 33, 8).astype(np.float32))
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal,Sq,Skv", [
    (True, 64, 64), (False, 64, 64), (True, 48, 33), (False, 48, 33)])
def test_flash_backward_matches_reference(causal, Sq, Skv):
    """Custom-VJP Pallas backward kernels vs autodiff of the dense
    reference, incl. ragged/padded shapes."""
    r = np.random.RandomState(7)
    q = jnp.asarray(r.randn(2, 2, Sq, 8).astype(np.float32))
    k = jnp.asarray(r.randn(2, 2, Skv, 8).astype(np.float32))
    v = jnp.asarray(r.randn(2, 2, Skv, 8).astype(np.float32))
    w = jnp.asarray(r.randn(2, 2, Sq, 8).astype(np.float32))

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=32,
                                block_k=32, interpret=True) * w).sum()

    def loss_r(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_gpt_train_step_through_pallas_kernel(hvd):
    """End-to-end: GPT train step differentiates through the kernel."""
    import optax
    from horovod_tpu.models.gpt import GPT, GPTConfig
    from horovod_tpu.parallel.mesh_utils import make_mesh
    from horovod_tpu.parallel.tp import gpt_partition_rules, shard_params
    from horovod_tpu.training import make_gspmd_train_step
    mesh = make_mesh(dp=8)
    cfg = GPTConfig(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                    max_seq_len=64, mesh=mesh, dtype=jnp.float32,
                    attention_impl="interpret")
    model = GPT(cfg)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 32)),
                      jnp.int32)
    tgts = jnp.roll(toks, -1, 1)
    v = model.init(jax.random.PRNGKey(0), toks)
    rules = gpt_partition_rules()
    params = shard_params(v["params"], mesh, rules)
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    from jax.sharding import PartitionSpec as P
    step = make_gspmd_train_step(model.apply, tx, mesh, rules,
                                 batch_spec=P("dp", None))
    params, opt, l1 = step(params, opt, toks, tgts)
    params, opt, l2 = step(params, opt, toks, tgts)
    assert np.isfinite(float(l2)) and float(l2) < float(l1)


class TestGQAKernel:
    """GQA-aware kernels: kv-width K/V read via block index maps, dK/dV
    accumulated across the group inside the kernel (never expanded in
    HBM). Oracle: the same computation with explicitly repeated K/V."""

    @pytest.mark.parametrize("kv_heads,seq", [(2, 64), (1, 64), (2, 50)])
    def test_gqa_matches_expanded(self, kv_heads, seq):
        from horovod_tpu.ops.pallas_attention import flash_attention
        from horovod_tpu.parallel.sp import expand_kv_heads
        rng = np.random.RandomState(0)
        B, H, D = 2, 4, 16
        q = jnp.asarray(rng.randn(B, H, seq, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, kv_heads, seq, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, kv_heads, seq, D), jnp.float32)
        ke, ve = expand_kv_heads(k, v, H // kv_heads)

        def f_gqa(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=32,
                                   block_k=32, interpret=True)

        out = f_gqa(q, k, v)
        ref = f_gqa(q, ke, ve)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

        # full VJP: dq matches; dk/dv match the group-summed expansion
        def loss_gqa(q, k, v):
            return jnp.sum(f_gqa(q, k, v).astype(jnp.float32) ** 2)

        gq, gk, gv = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
        gqe, gke, gve = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, ke, ve)
        G = H // kv_heads
        np.testing.assert_allclose(np.asarray(gq), np.asarray(gqe),
                                   rtol=2e-4, atol=2e-4)
        for got, exp in ((gk, gke), (gv, gve)):
            exp_summed = np.asarray(exp).reshape(
                B, kv_heads, G, seq, D).sum(axis=2)
            np.testing.assert_allclose(np.asarray(got), exp_summed,
                                       rtol=2e-4, atol=2e-4)

    def test_gqa_rejects_indivisible(self):
        from horovod_tpu.ops.pallas_attention import flash_attention
        q = jnp.zeros((1, 4, 16, 8))
        k = v = jnp.zeros((1, 3, 16, 8))
        with pytest.raises(ValueError, match="multiple"):
            flash_attention(q, k, v, interpret=True)
