"""Llama family: RMSNorm/RoPE/SwiGLU/GQA correctness and the dp x tp and
sp (ring) train paths on the 8-device mesh.

The reference framework has no model zoo requirement here; this family
demonstrates the parallelism stack on the dominant open-weight LM
architecture (see models/llama.py docstring)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.llama import (Llama, LlamaConfig, apply_rope,
                                      llama_partition_rules,
                                      rope_frequencies)
from horovod_tpu.parallel.mesh_utils import make_mesh
from horovod_tpu.parallel.tp import shard_params
from horovod_tpu.training import make_gspmd_train_step


def _tiny(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("head_dim", 8)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("attention_impl", "reference")
    return LlamaConfig(**kw)


class TestRoPE:
    def test_rotation_preserves_norm(self):
        angles = rope_frequencies(8, 16, 10000.0)
        x = jnp.asarray(np.random.RandomState(0).randn(1, 2, 16, 8),
                        jnp.float32)
        y = apply_rope(x, angles)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)

    def test_relative_phase(self):
        # q.k after RoPE depends only on relative offset: rotating both
        # by one extra position leaves the dot product unchanged
        angles = rope_frequencies(8, 16, 10000.0)
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 1, 1, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 1, 1, 8), jnp.float32)
        def dot(i, j):
            qi = apply_rope(q, angles[i:i + 1])
            kj = apply_rope(k, angles[j:j + 1])
            return float(jnp.sum(qi * kj))
        assert dot(3, 1) == pytest.approx(dot(7, 5), rel=1e-4)
        assert dot(3, 1) != pytest.approx(dot(3, 2), rel=1e-2)


class TestLlamaModel:
    def test_forward_shape_finite(self):
        cfg = _tiny()
        model = Llama(cfg)
        toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
        v = model.init(jax.random.PRNGKey(0), toks)
        out = model.apply(v, toks)
        assert out.shape == (2, 16, 64)
        assert np.isfinite(np.asarray(out)).all()

    def test_logits_dtype_knob(self):
        """logits_dtype (round-5 measured lever, +4.8% on chip): the
        default stays f32; bf16 must actually reach the lm_head output
        AND still train through the fused-CE loss path."""
        toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
        for want, cfg in ((jnp.float32, _tiny()),
                          (jnp.bfloat16,
                           _tiny(logits_dtype=jnp.bfloat16))):
            model = Llama(cfg)
            v = model.init(jax.random.PRNGKey(0), toks)
            assert model.apply(v, toks).dtype == want

        import optax
        from horovod_tpu.parallel.mesh_utils import make_mesh
        from horovod_tpu.parallel.tp import shard_params
        from horovod_tpu.models.llama import llama_partition_rules
        import horovod_tpu as hvd
        hvd.init()
        mesh = make_mesh(dp=hvd.size())
        cfg = _tiny(logits_dtype=jnp.bfloat16, mesh=mesh)
        model = Llama(cfg)
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        params = shard_params(params, mesh, llama_partition_rules())
        tx = optax.adamw(1e-3)
        step = make_gspmd_train_step(model.apply, tx, mesh,
                                     llama_partition_rules())
        big = jnp.asarray(np.random.RandomState(1).randint(
            0, 64, (hvd.size(), 16)))
        params, opt, loss = step(params, tx.init(params), big,
                                 jnp.roll(big, -1, axis=1))
        assert np.isfinite(float(loss))
        hvd.shutdown()

    def test_gqa_param_shapes(self):
        cfg = _tiny(num_heads=4, num_kv_heads=2)
        model = Llama(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        a = params["layers_0"]["attn"]
        assert a["wq"]["kernel"].shape == (32, 32)
        assert a["wk"]["kernel"].shape == (32, 16)   # 2 kv heads x 8
        assert a["wv"]["kernel"].shape == (32, 16)

    def test_gqa_rejects_bad_ratio(self):
        with pytest.raises(ValueError, match="multiple"):
            _tiny(num_heads=4, num_kv_heads=3)

    def test_gqa_equals_mha_with_repeated_kv(self):
        """kv_heads=1 must equal an MHA whose kv projections are the
        broadcast of the single kv head."""
        cfg_g = _tiny(num_heads=2, num_kv_heads=1, num_layers=1)
        cfg_m = _tiny(num_heads=2, num_kv_heads=2, num_layers=1)
        toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (1, 8)))
        mg = Llama(cfg_g)
        pg = mg.init(jax.random.PRNGKey(0), toks)["params"]
        pm = jax.tree.map(lambda x: x, pg)
        a = dict(pm["layers_0"]["attn"])
        a["wk"] = {"kernel": jnp.concatenate([a["wk"]["kernel"]] * 2, 1)}
        a["wv"] = {"kernel": jnp.concatenate([a["wv"]["kernel"]] * 2, 1)}
        pm = {**pm, "layers_0": {**pm["layers_0"],
                                 "attn": {**pm["layers_0"]["attn"], **a}}}
        out_g = mg.apply({"params": pg}, toks)
        out_m = Llama(cfg_m).apply({"params": pm}, toks)
        np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_m),
                                   atol=1e-5)

    def test_rejects_overlong_sequence(self):
        cfg = _tiny(max_seq_len=16)
        model = Llama(cfg)
        toks = jnp.zeros((1, 32), jnp.int32)
        with pytest.raises(ValueError, match="exceeds"):
            model.init(jax.random.PRNGKey(0), toks)

    def test_causality(self):
        cfg = _tiny(num_layers=1)
        model = Llama(cfg)
        rng = np.random.RandomState(2)
        t1 = rng.randint(0, 64, (1, 16))
        t2 = t1.copy()
        t2[0, 10:] = rng.randint(0, 64, 6)   # mutate the future only
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
        o1 = np.asarray(model.apply(v, jnp.asarray(t1)))
        o2 = np.asarray(model.apply(v, jnp.asarray(t2)))
        np.testing.assert_allclose(o1[0, :10], o2[0, :10], atol=1e-5)
        assert not np.allclose(o1[0, 10:], o2[0, 10:], atol=1e-5)


class TestLlamaParallel:
    def test_dp_tp_train_step(self, hvd):
        mesh = make_mesh(dp=2, tp=4)
        cfg = _tiny(mesh=mesh, num_heads=4, num_kv_heads=4)
        model = Llama(cfg)
        toks = np.random.RandomState(0).randint(0, 64, (4, 16)).astype(
            np.int32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.asarray(toks))["params"]
        rules = llama_partition_rules()
        sharded = shard_params(params, mesh, rules)
        tx = optax.adam(1e-2)
        step = make_gspmd_train_step(model.apply, tx, mesh, rules,
                                     batch_spec=P("dp", None))
        opt = tx.init(sharded)
        losses = []
        p, o = sharded, opt
        t = jnp.asarray(toks)
        tgt = jnp.asarray(np.roll(toks, -1, 1))
        for _ in range(5):
            p, o, loss = step(p, o, t, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # wq column-parallel: feature dim sharded over tp
        wq = p["layers_0"]["attn"]["wq"]["kernel"]
        assert wq.sharding.spec == P(None, "tp")

    @pytest.mark.parametrize("attention", ["ring", "zigzag"])
    def test_ring_sp_matches_dense(self, hvd, attention):
        # GQA kv-width blocks circulate the ring (2 kv heads, 4 q heads);
        # zigzag additionally permutes the residual stream + RoPE windows
        mesh = make_mesh(dp=2, sp=4)
        cfg_r = _tiny(mesh=mesh, attention=attention, num_kv_heads=2)
        cfg_d = _tiny(num_kv_heads=2)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (2, 32)), jnp.int32)
        model_r, model_d = Llama(cfg_r), Llama(cfg_d)
        v = model_d.init(jax.random.PRNGKey(0), toks)
        out_d = np.asarray(model_d.apply(v, toks))
        out_r = np.asarray(model_r.apply(v, toks))
        np.testing.assert_allclose(out_r, out_d, atol=2e-4)

    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_ulysses_sp_matches_dense(self, hvd, kv_heads):
        # kv=4 splits across the 4-way sp axis (kv-width all_to_all);
        # kv=2 exercises the pre-broadcast fallback (2 % 4 != 0)
        mesh = make_mesh(dp=2, sp=4)
        cfg_u = _tiny(mesh=mesh, attention="ulysses", num_heads=8,
                      num_kv_heads=kv_heads)
        cfg_d = _tiny(num_heads=8, num_kv_heads=kv_heads)
        toks = jnp.asarray(
            np.random.RandomState(2).randint(0, 64, (2, 32)), jnp.int32)
        model_u, model_d = Llama(cfg_u), Llama(cfg_d)
        v = model_d.init(jax.random.PRNGKey(1), toks)
        out_d = np.asarray(model_d.apply(v, toks))
        out_u = np.asarray(model_u.apply(v, toks))
        np.testing.assert_allclose(out_u, out_d, atol=2e-4)


class TestRemat:
    def test_remat_matches_no_remat(self):
        """Activation checkpointing must not change the math."""
        toks = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 16)))
        tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 1))
        outs = []
        for remat in (False, True):
            cfg = _tiny(remat=remat)
            model = Llama(cfg)
            v = model.init(jax.random.PRNGKey(0), toks)

            def loss_fn(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgts).mean()

            loss, grads = jax.value_and_grad(loss_fn)(v["params"])
            outs.append((float(loss), grads))
        np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
            outs[0][1], outs[1][1])

    def test_remat_ring_sp(self, hvd):
        """remat must compose with the shard_map ring-attention path
        (jax.checkpoint over shard_map is historically fragile)."""
        mesh = make_mesh(dp=2, sp=4)
        cfg = _tiny(mesh=mesh, attention="ring", num_kv_heads=2,
                    remat=True)
        model = Llama(cfg)
        toks = jnp.asarray(
            np.random.RandomState(5).randint(0, 64, (2, 32)), jnp.int32)
        v = model.init(jax.random.PRNGKey(0), toks)
        # must be jitted: eager remat (closed_call) inside shard_map is
        # unsupported by jax; the training path always jits
        g = jax.jit(jax.grad(
            lambda p: model.apply({"params": p}, toks).sum()))(v["params"])
        assert np.isfinite(np.asarray(
            jax.tree.leaves(g)[0], np.float32)).all()

    def test_remat_gpt(self):
        from horovod_tpu.models.gpt import GPT, GPTConfig
        toks = jnp.asarray(np.random.RandomState(4).randint(0, 64, (2, 16)))
        cfg = GPTConfig(vocab_size=64, num_layers=1, num_heads=2,
                        head_dim=8, max_seq_len=16, dtype=jnp.float32,
                        attention_impl="reference", remat=True)
        model = GPT(cfg)
        v = model.init(jax.random.PRNGKey(0), toks)
        g = jax.grad(lambda p: model.apply({"params": p}, toks).sum())(
            v["params"])
        assert np.isfinite(np.asarray(
            jax.tree.leaves(g)[0], np.float32)).all()


class TestFlashSP:
    """Ring/Ulysses attention with the Pallas flash kernel per step
    (flash-decoding-style LSE merging) must match the lax sp path."""

    @pytest.mark.parametrize("attention", ["ring", "ulysses", "zigzag"])
    def test_flash_sp_matches_lax_sp(self, hvd, attention):
        mesh = make_mesh(dp=2, sp=4)
        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 64, (2, 32)), jnp.int32)
        heads = 8 if attention == "ulysses" else 4
        cfg_l = _tiny(mesh=mesh, attention=attention, num_heads=heads,
                      num_kv_heads=2, attention_impl="reference")
        cfg_f = _tiny(mesh=mesh, attention=attention, num_heads=heads,
                      num_kv_heads=2, attention_impl="interpret")
        model_l, model_f = Llama(cfg_l), Llama(cfg_f)
        v = model_l.init(jax.random.PRNGKey(0), toks)
        out_l = np.asarray(jax.jit(
            lambda v, t: model_l.apply(v, t))(v, toks))
        out_f = np.asarray(jax.jit(
            lambda v, t: model_f.apply(v, t))(v, toks))
        np.testing.assert_allclose(out_f, out_l, atol=2e-4)

    def test_flash_ring_grads_match(self, hvd):
        mesh = make_mesh(dp=2, sp=4)
        toks = jnp.asarray(
            np.random.RandomState(4).randint(0, 64, (2, 32)), jnp.int32)
        outs = []
        for impl in ("reference", "interpret"):
            cfg = _tiny(mesh=mesh, attention="ring", num_kv_heads=2,
                        attention_impl=impl)
            model = Llama(cfg)
            v = model.init(jax.random.PRNGKey(0), toks)
            g = jax.jit(jax.grad(
                lambda p: model.apply({"params": p}, toks).sum()))(
                v["params"])
            outs.append(g)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4), outs[0], outs[1])


class TestFlashSPTracing:
    def test_flash_impl_traces_with_vma_checking(self, hvd):
        """The production attention_impl='pallas' path (check_vma=True)
        must trace: every cond branch and scan carry has to yield
        sp-varying types (a plain jnp.zeros constant would not)."""
        from functools import partial
        from horovod_tpu.parallel.sp import (ring_attention,
                                             ulysses_attention)
        mesh = make_mesh(dp=2, sp=4)
        spec = P(None, None, "sp", None)
        q = jnp.zeros((2, 4, 64, 16), jnp.float32)
        k = v = jnp.zeros((2, 2, 64, 16), jnp.float32)
        for attn in (ring_attention, ulysses_attention):
            f = jax.shard_map(
                partial(attn, axis_name="sp", causal=True, impl="flash"),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
            out = jax.eval_shape(f, q, k, v)
            assert out.shape == (2, 4, 64, 16)
