"""ISSUE 15 disaggregated-serving acceptance (slow tier): REAL
prefill + decode worker OS processes behind a DisaggRouter, driven
through the seeded ``profile="disagg"`` plan by the soak harness.

The plan SIGKILLs one PREFILL worker mid-traffic, severs one
KV-block migration with a ``serve.migrate`` ``conn_reset`` AFTER its
frame landed, and flips a payload bit pre-framing inside a
``corrupt`` window, while a fresh weight version is published
mid-incident. The bar (docs/serving.md, disaggregation section):

* migration actually carried traffic (decode-pool installs > 0),
* the corrupt was caught by the per-BLOCK crc on arrival — before
  any token could be generated from the migrated cache,
* the severed migration recovered: the ladder replay was served the
  decode endpoint's deduped install ack, or the request re-prefilled
  exactly once,
* migration chaos never escalated into an ejection (failovers ==
  scheduled kills exactly),
* the killed prefill worker was ejected by the accrual sweep within
  2 x suspect_s and respawned on the newest published weights,
* every request answered exactly once or shed with retry-after; p99
  and error-rate SLOs hold outside the bounded recovery windows.

Driven through the tools/serve_soak.py --disagg CLI so the CLI
contract is covered by the same run. Mirrors
test_serve_fleet_soak.py, including the 3-consecutive-green
requirement verified at PR time.
"""
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.mark.slow
def test_serve_disagg_soak_acceptance(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_soak.py"),
         "--disagg", "--prefill", "2", "--decode", "1",
         "--clients", "4", "--seed", "7", "--out", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=480)
    assert out.stdout.strip(), out.stderr[-3000:]
    verdict = json.loads(out.stdout)
    detail = json.dumps(verdict, indent=2, sort_keys=True)[:3000]
    assert verdict["disagg"] is True, detail
    assert verdict["no_silent_drops"] is True, detail
    assert verdict["answered_once"] is True, detail
    assert verdict["shed_carry_retry_after"] is True, detail
    # the migration plane actually ran, under faults
    assert verdict["migrations_ok"] is True, detail
    assert verdict["migrations_in"] > 0, detail
    assert verdict["migrate_corrupt_caught"] is True, detail
    assert verdict["migrate_corrupt_detected"] > 0, detail
    assert verdict["migrate_blips_recovered"] is True, detail
    # migration chaos must never escalate into an ejection
    assert verdict["failovers_only_kills"] is True, detail
    # the prefill kill: accrual detection, bounded; weight-gated respawn
    assert verdict["failover_bounded"] is True, detail
    assert verdict["failover_s"] <= 2 * verdict["suspect_s"], detail
    assert verdict["respawned_on_newest"] is True, detail
    assert verdict["capacity_restored"] is True, detail
    assert verdict["slo_held"] is True, detail
    assert verdict["ok"] is True, detail
