"""Elastic tests: state commit/restore/sync, sampler re-partitioning,
driver with fake discovery + mock workers, run wrapper recovery.

Mirrors test/single/test_elastic_driver.py (fake discovery scripts, mock
workers) and test/single/test_torch_elastic.py (state save/restore)."""
import os
import stat
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.core.types import (HorovodInternalError,
                                    HostsUpdatedInterrupt)
from horovod_tpu.elastic import (ElasticDriver, ElasticSampler,
                                 FixedHostDiscovery, HostManager, State,
                                 TrainState)
from horovod_tpu.elastic.discovery import HostDiscoveryScript


class TestState:
    def test_commit_restore(self, hvd):
        s = State(epoch=1, w=np.ones(3))
        s.epoch = 5
        s.w = np.zeros(3)
        s.restore()
        assert s.epoch == 1
        np.testing.assert_array_equal(s.w, np.ones(3))

    def test_commit_saves(self, hvd):
        s = State(epoch=0)
        s.epoch = 2
        s.commit()
        s.epoch = 9
        s.restore()
        assert s.epoch == 2

    def test_sync_pytree(self, hvd):
        s = TrainState(params={"w": jnp.ones((4,))}, epoch=3)
        s.sync()
        assert s.epoch == 3
        np.testing.assert_array_equal(np.asarray(s.params["w"]), np.ones(4))

    def test_reset_callbacks(self, hvd):
        calls = []
        s = State(x=1)
        s.register_reset_callbacks([lambda: calls.append(1)])
        s.on_reset()
        assert calls == [1]


class TestSampler:
    def test_partition_across_replicas(self):
        samplers = [ElasticSampler(12, shuffle=False, num_replicas=3, rank=r)
                    for r in range(3)]
        seen = sorted(i for s in samplers for i in s)
        assert seen == list(range(12))

    def test_reset_repartitions_unprocessed(self):
        s = ElasticSampler(12, shuffle=False, num_replicas=3, rank=0)
        s.record_indices([0, 1, 2, 3, 4, 5])
        s.reset(num_replicas=2, rank=0)
        s2 = ElasticSampler(12, shuffle=False, num_replicas=2, rank=1)
        s2.record_indices([0, 1, 2, 3, 4, 5])
        s2.reset(num_replicas=2, rank=1)
        remaining = sorted(set(list(s) + list(s2)))
        assert remaining == [6, 7, 8, 9, 10, 11]

    def test_epoch_clears_progress(self):
        s = ElasticSampler(8, shuffle=True, num_replicas=2, rank=0)
        s.record_indices(list(range(8)))
        s.set_epoch(1)
        assert len(s) == 4


class TestHostManager:
    def test_blacklist_and_resurrect(self, monkeypatch):
        disc = FixedHostDiscovery({"a": 1, "b": 1})
        mgr = HostManager(disc)
        assert {h.hostname for h in mgr.current_hosts()} == {"a", "b"}
        mgr.blacklist("b")
        assert {h.hostname for h in mgr.current_hosts()} == {"a"}
        # simulate cooldown expiry
        mgr.states["b"]._until = 0.0
        assert {h.hostname for h in mgr.current_hosts()} == {"a", "b"}

    def test_discovery_script(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\necho otherhost:1\n")
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        d = HostDiscoveryScript(str(script))
        assert d.find_available_hosts_and_slots() == {"localhost": 2,
                                                      "otherhost": 1}


class TestElasticDriver:
    def test_completes_on_success(self):
        disc = FixedHostDiscovery({"localhost": 2})
        driver = ElasticDriver(disc, ["true"], min_np=1, poll_interval=0.1)
        assert driver.run() == 0

    def test_worker_failure_blacklists_and_respects_reset_limit(self):
        disc = FixedHostDiscovery({"localhost": 1})
        driver = ElasticDriver(disc, ["false"], min_np=1, reset_limit=1,
                               poll_interval=0.05)
        with pytest.raises(RuntimeError, match="reset_limit"):
            driver.run()
        assert driver.resets >= 1

    def test_host_change_triggers_reset(self, tmp_path):
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:1\n")
        script = tmp_path / "discover.sh"
        script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        disc = HostDiscoveryScript(str(script))
        driver = ElasticDriver(disc, ["sleep", "30"], min_np=1,
                               reset_limit=0, poll_interval=0.05)

        def mutate():
            time.sleep(0.5)
            hosts_file.write_text("localhost:2\n")  # topology change

        t = threading.Thread(target=mutate)
        t.start()
        with pytest.raises(RuntimeError, match="reset_limit"):
            driver.run()
        t.join()


class TestRunWrapper:
    def test_recovers_from_internal_error(self, hvd):
        from horovod_tpu.elastic import run as elastic_run
        attempts = []

        @elastic_run
        def train(state):
            attempts.append(state.epoch)
            if len(attempts) < 2:
                state.epoch = 99   # uncommitted progress, must roll back
                raise HorovodInternalError("fake comm failure")
            return state.epoch

        s = State(epoch=7)
        assert train(s) == 7
        assert len(attempts) == 2

    def test_hosts_updated_commits(self, hvd):
        from horovod_tpu.elastic import run as elastic_run
        attempts = []

        @elastic_run
        def train(state):
            attempts.append(1)
            if len(attempts) < 2:
                state.epoch = 42
                raise HostsUpdatedInterrupt()
            return state.epoch

        s = State(epoch=0)
        assert train(s) == 42   # HostsUpdated commits in-flight progress

    def test_reset_limit(self, hvd):
        from horovod_tpu.elastic import run as elastic_run

        @elastic_run
        def train(state):
            raise HorovodInternalError("always fails")

        with pytest.raises(RuntimeError, match="reset limit"):
            train(State(epoch=0), reset_limit=2)

    def test_notification_manager_check(self, hvd):
        from horovod_tpu.elastic import notification_manager
        notification_manager.init()
        notification_manager.handle_hosts_updated()
        with pytest.raises(HostsUpdatedInterrupt):
            notification_manager.check()
        notification_manager.check()  # cleared


class TestElasticHybrid:
    """Elastic x hybrid parallelism semantics (VERDICT r3 item 9): the
    model-parallel factorization is fixed, dp absorbs elasticity, and an
    incompatible world fails fast with MeshResizeError."""

    def test_spec_resizes_dp_only(self, hvd):
        import jax
        from horovod_tpu.elastic import ElasticMeshSpec
        spec = ElasticMeshSpec(tp=2)
        devs = jax.devices()
        m8 = spec.build(devs)                     # 8 -> dp=4, tp=2
        assert m8.shape["dp"] == 4 and m8.shape["tp"] == 2
        m4 = spec.build(devs[:4])                 # shrink -> dp=2, tp=2
        assert m4.shape["dp"] == 2 and m4.shape["tp"] == 2
        m2 = spec.build(devs[:2])                 # minimum: dp=1
        # make_mesh drops size-1 axes (rules restrict to present axes)
        assert dict(m2.shape).get("dp", 1) == 1 and m2.shape["tp"] == 2

    def test_spec_rejects_misfit_world(self, hvd):
        import jax
        from horovod_tpu.elastic import ElasticMeshSpec, MeshResizeError
        devs = jax.devices()
        spec = ElasticMeshSpec(tp=2)
        with pytest.raises(MeshResizeError) as e:
            spec.build(devs[:3])                  # odd world under tp=2
        assert "multiple of 2" in str(e.value)
        with pytest.raises(MeshResizeError):
            ElasticMeshSpec(tp=2, sp=2).build(devs[:2])   # below fixed
        # the unit named in the message is tp*sp*pp*ep
        with pytest.raises(MeshResizeError) as e:
            ElasticMeshSpec(tp=2, pp=2).build(devs[:6])
        assert "multiple of 4" in str(e.value)

    def test_gspmd_state_reshards_on_sync(self, hvd):
        import jax
        import numpy as np
        from horovod_tpu.elastic import ElasticMeshSpec, GSPMDState
        from horovod_tpu.parallel.tp import PartitionRules
        from jax.sharding import PartitionSpec as P

        rules = PartitionRules([(r"w", P(None, "tp"))])
        spec = ElasticMeshSpec(tp=2)
        w = np.arange(32, dtype=np.float32).reshape(4, 8)
        state = GSPMDState(spec, rules, params={"w": w}, epoch=0)

        state.sync()
        placed = state.placed("params")["w"]
        assert placed.sharding.mesh.shape["dp"] == 4
        np.testing.assert_array_equal(np.asarray(placed), w)
        # tracked values stay snapshot-able host-side trees (the
        # broadcast/snapshot/checkpoint contract): every leaf fully
        # addressable — placement is a view, not the stored value
        assert state.params["w"].is_fully_addressable \
            if hasattr(state.params["w"], "is_fully_addressable") else True
        np.testing.assert_array_equal(np.asarray(state.params["w"]), w)

        # trained device trees flow back as host trees...
        state.update_from_device(params={"w": placed * 2})
        assert isinstance(state.params["w"], np.ndarray)
        np.testing.assert_array_equal(state.params["w"], w * 2)
        state.update_from_device(params={"w": placed})

        # simulate an elastic shrink: fewer devices -> smaller dp, same
        # tp sharding, values preserved (reshard-on-restore)
        import jax as _jax
        spec2 = ElasticMeshSpec(tp=2)
        state._spec = spec2
        orig_build = spec2.build
        spec2.build = lambda devices=None: orig_build(_jax.devices()[:4])
        state.sync()
        placed = state.placed("params")["w"]
        assert placed.sharding.mesh.shape["dp"] == 2
        np.testing.assert_array_equal(np.asarray(placed), w)
        # place() puts auxiliary trees on the same mesh
        aux = state.place({"w": w * 2})
        assert aux["w"].sharding.mesh.shape["dp"] == 2
        # a second sync (in-process reset path) keeps working even with
        # a device tree stored: it is normalized back to host first
        state._values["params"] = {"w": placed}
        state.sync()
        np.testing.assert_array_equal(np.asarray(state.params["w"]), w)

    def test_gspmd_state_sync_fails_fast_on_misfit(self, hvd):
        import jax
        from horovod_tpu.elastic import (ElasticMeshSpec, GSPMDState,
                                         MeshResizeError)
        from horovod_tpu.parallel.tp import PartitionRules
        from jax.sharding import PartitionSpec as P
        spec = ElasticMeshSpec(tp=2)
        orig = spec.build
        spec.build = lambda devices=None: orig(jax.devices()[:3])
        state = GSPMDState(spec, PartitionRules([]), params=None)
        with pytest.raises(MeshResizeError):
            state.sync()
