"""Elastic tests: state commit/restore/sync, sampler re-partitioning,
driver with fake discovery + mock workers, run wrapper recovery.

Mirrors test/single/test_elastic_driver.py (fake discovery scripts, mock
workers) and test/single/test_torch_elastic.py (state save/restore)."""
import os
import stat
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.core.types import (HorovodInternalError,
                                    HostsUpdatedInterrupt)
from horovod_tpu.elastic import (ElasticDriver, ElasticSampler,
                                 FixedHostDiscovery, HostManager, State,
                                 TrainState)
from horovod_tpu.elastic.discovery import HostDiscoveryScript


class TestState:
    def test_commit_restore(self, hvd):
        s = State(epoch=1, w=np.ones(3))
        s.epoch = 5
        s.w = np.zeros(3)
        s.restore()
        assert s.epoch == 1
        np.testing.assert_array_equal(s.w, np.ones(3))

    def test_commit_saves(self, hvd):
        s = State(epoch=0)
        s.epoch = 2
        s.commit()
        s.epoch = 9
        s.restore()
        assert s.epoch == 2

    def test_sync_pytree(self, hvd):
        s = TrainState(params={"w": jnp.ones((4,))}, epoch=3)
        s.sync()
        assert s.epoch == 3
        np.testing.assert_array_equal(np.asarray(s.params["w"]), np.ones(4))

    def test_reset_callbacks(self, hvd):
        calls = []
        s = State(x=1)
        s.register_reset_callbacks([lambda: calls.append(1)])
        s.on_reset()
        assert calls == [1]


class TestSampler:
    def test_partition_across_replicas(self):
        samplers = [ElasticSampler(12, shuffle=False, num_replicas=3, rank=r)
                    for r in range(3)]
        seen = sorted(i for s in samplers for i in s)
        assert seen == list(range(12))

    def test_reset_repartitions_unprocessed(self):
        s = ElasticSampler(12, shuffle=False, num_replicas=3, rank=0)
        s.record_indices([0, 1, 2, 3, 4, 5])
        s.reset(num_replicas=2, rank=0)
        s2 = ElasticSampler(12, shuffle=False, num_replicas=2, rank=1)
        s2.record_indices([0, 1, 2, 3, 4, 5])
        s2.reset(num_replicas=2, rank=1)
        remaining = sorted(set(list(s) + list(s2)))
        assert remaining == [6, 7, 8, 9, 10, 11]

    def test_epoch_clears_progress(self):
        s = ElasticSampler(8, shuffle=True, num_replicas=2, rank=0)
        s.record_indices(list(range(8)))
        s.set_epoch(1)
        assert len(s) == 4


class TestHostManager:
    def test_blacklist_and_resurrect(self, monkeypatch):
        disc = FixedHostDiscovery({"a": 1, "b": 1})
        mgr = HostManager(disc)
        assert {h.hostname for h in mgr.current_hosts()} == {"a", "b"}
        mgr.blacklist("b")
        assert {h.hostname for h in mgr.current_hosts()} == {"a"}
        # simulate cooldown expiry
        mgr.states["b"]._until = 0.0
        assert {h.hostname for h in mgr.current_hosts()} == {"a", "b"}

    def test_discovery_script(self, tmp_path):
        script = tmp_path / "discover.sh"
        script.write_text("#!/bin/sh\necho localhost:2\necho otherhost:1\n")
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        d = HostDiscoveryScript(str(script))
        assert d.find_available_hosts_and_slots() == {"localhost": 2,
                                                      "otherhost": 1}


class TestElasticDriver:
    def test_completes_on_success(self):
        disc = FixedHostDiscovery({"localhost": 2})
        driver = ElasticDriver(disc, ["true"], min_np=1, poll_interval=0.1)
        assert driver.run() == 0

    def test_worker_failure_blacklists_and_respects_reset_limit(self):
        disc = FixedHostDiscovery({"localhost": 1})
        driver = ElasticDriver(disc, ["false"], min_np=1, reset_limit=1,
                               poll_interval=0.05)
        with pytest.raises(RuntimeError, match="reset_limit"):
            driver.run()
        assert driver.resets >= 1

    def test_host_change_triggers_reset(self, tmp_path):
        hosts_file = tmp_path / "hosts.txt"
        hosts_file.write_text("localhost:1\n")
        script = tmp_path / "discover.sh"
        script.write_text(f"#!/bin/sh\ncat {hosts_file}\n")
        script.chmod(script.stat().st_mode | stat.S_IEXEC)
        disc = HostDiscoveryScript(str(script))
        driver = ElasticDriver(disc, ["sleep", "30"], min_np=1,
                               reset_limit=0, poll_interval=0.05)

        def mutate():
            time.sleep(0.5)
            hosts_file.write_text("localhost:2\n")  # topology change

        t = threading.Thread(target=mutate)
        t.start()
        with pytest.raises(RuntimeError, match="reset_limit"):
            driver.run()
        t.join()


class TestRunWrapper:
    def test_recovers_from_internal_error(self, hvd):
        from horovod_tpu.elastic import run as elastic_run
        attempts = []

        @elastic_run
        def train(state):
            attempts.append(state.epoch)
            if len(attempts) < 2:
                state.epoch = 99   # uncommitted progress, must roll back
                raise HorovodInternalError("fake comm failure")
            return state.epoch

        s = State(epoch=7)
        assert train(s) == 7
        assert len(attempts) == 2

    def test_hosts_updated_commits(self, hvd):
        from horovod_tpu.elastic import run as elastic_run
        attempts = []

        @elastic_run
        def train(state):
            attempts.append(1)
            if len(attempts) < 2:
                state.epoch = 42
                raise HostsUpdatedInterrupt()
            return state.epoch

        s = State(epoch=0)
        assert train(s) == 42   # HostsUpdated commits in-flight progress

    def test_reset_limit(self, hvd):
        from horovod_tpu.elastic import run as elastic_run

        @elastic_run
        def train(state):
            raise HorovodInternalError("always fails")

        with pytest.raises(RuntimeError, match="reset limit"):
            train(State(epoch=0), reset_limit=2)

    def test_notification_manager_check(self, hvd):
        from horovod_tpu.elastic import notification_manager
        notification_manager.init()
        notification_manager.handle_hosts_updated()
        with pytest.raises(HostsUpdatedInterrupt):
            notification_manager.check()
        notification_manager.check()  # cleared
