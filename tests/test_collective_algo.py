"""Topology-aware collective algorithm plane (ISSUE 6).

Covers: the ops/algo.py registry + alpha-beta cost model + resolution
precedence, numerical parity of every allreduce strategy (direct /
rs_ag / rhd / two_level) against numpy oracles, the quantized int8
allgather / reducescatter / alltoall variants (>=3.5x wire-byte
acceptance bar, bounded error, non-float passthrough, DCN-only routing
through the two-level cross.py variants), engine routing + wire
accounting, rank-invariant execution-time resolution (a tuner flip
cannot diverge ranks), the autotuner's per-regime categorical dims
(converging to DIFFERENT algorithms for small vs large buckets — the
ROADMAP item-1 bar), the deterministic-tuner replay regression, the
hvd_collective_algo_total counter + ALGO timeline row, and the
two-level fail-fast mesh check.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _stacked(n, shape, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(n, *shape).astype(dtype)


def _algo_count(algo, collective="allreduce"):
    from horovod_tpu import obs
    c = obs.get_registry().get("hvd_collective_algo_total",
                               {"algo": algo, "collective": collective})
    return 0 if c is None else int(c.value)


def _wire_count(kind):
    from horovod_tpu import obs
    c = obs.get_registry().get("hvd_wire_bytes_total", {"kind": kind})
    return 0 if c is None else int(c.value)


# -- cost model + resolution (pure math, no hvd state) ---------------------

def test_predict_cost_shapes_the_expected_crossovers():
    from horovod_tpu.ops import algo
    # latency regime, big power-of-two world: rhd's 2*log2(P) hops beat
    # the ring's 2*(P-1)
    assert algo.predict_cost("rhd", 1024, 64) < \
        algo.predict_cost("direct", 1024, 64)
    # bandwidth regime: all flat algorithms share the ring byte term, so
    # direct's single launch wins in-model
    big = 64 << 20
    assert algo.predict_cost("direct", big, 64) <= \
        algo.predict_cost("rs_ag", big, 64)
    # DCN + hierarchy: the cross phase moves N/local, so two_level wins
    # the bandwidth-bound regime
    assert algo.predict_cost("two_level", big, 64, hier_shape=(8, 8),
                             dcn=True) < \
        algo.predict_cost("direct", big, 64, dcn=True)
    # structural illegality costs infinity
    assert algo.predict_cost("rhd", 1024, 6) == float("inf")
    assert algo.predict_cost("two_level", 1024, 8) == float("inf")
    with pytest.raises(ValueError, match="unknown collective algorithm"):
        algo.predict_cost("ring3", 1, 8)


def test_select_algorithm_and_crossover():
    from horovod_tpu.ops import algo
    assert algo.select_algorithm(1024, 64) == "rhd"
    assert algo.select_algorithm(64 << 20, 64) == "direct"
    assert algo.select_algorithm(64 << 20, 64, hier_shape=(8, 8),
                                 dcn=True) == "two_level"
    assert algo.select_algorithm(1024, 1) == "direct"
    # closed form: N* = alpha * P / beta of the dominant link
    assert algo.crossover_bytes(8) == int(
        algo.ICI.alpha_s * 8 / algo.ICI.beta_s_per_byte)
    assert algo.crossover_bytes(8, dcn=True) > algo.crossover_bytes(8)
    # deterministic: same inputs, same answer
    for _ in range(3):
        assert algo.select_algorithm(1024, 64) == "rhd"


def test_resolve_precedence_and_legalization():
    from horovod_tpu.core.config import Config
    from horovod_tpu.ops import algo
    cfg = Config()
    # default: cost model (small world -> direct)
    assert algo.resolve(cfg, 4096, 8) == "direct"
    # tuner-learned per-regime choices split at the threshold
    cfg.collective_algo_small = "rhd"
    cfg.collective_algo_large = "rs_ag"
    cfg.collective_algo_threshold_bytes = 1 << 20
    assert algo.resolve(cfg, 4096, 8) == "rhd"
    assert algo.resolve(cfg, 2 << 20, 8) == "rs_ag"
    # rhd on a non-power-of-two world legalizes to direct (tuner choice,
    # not explicit)
    assert algo.resolve(cfg, 4096, 6) == "direct"
    # legacy toggles force two_level when the hierarchy is real
    cfg2 = Config()
    cfg2.hierarchical_allreduce = True
    assert algo.resolve(cfg2, 4096, 8, hier_ok=True) == "two_level"
    assert algo.resolve(cfg2, 4096, 8, hier_ok=False) == "direct"
    # explicit HOROVOD_COLLECTIVE_ALGO beats everything
    cfg.collective_algo, cfg.collective_algo_set = "rs_ag", True
    assert algo.resolve(cfg, 4096, 8) == "rs_ag"
    # ... and an explicit structurally-impossible rhd fails fast
    cfg3 = Config()
    cfg3.collective_algo, cfg3.collective_algo_set = "rhd", True
    with pytest.raises(ValueError, match="power-of-two"):
        algo.resolve(cfg3, 4096, 6)
    # per-call request beats config
    assert algo.resolve(cfg, 4096, 8, requested="direct") == "direct"


def test_config_validates_algo_knobs():
    from horovod_tpu.core.config import Config
    c = Config()
    c.collective_algo = "ring"
    with pytest.raises(ValueError, match="HOROVOD_COLLECTIVE_ALGO"):
        c.validate()
    c = Config()
    c.collective_algo_small = "bogus"
    with pytest.raises(ValueError, match="collective_algo_small"):
        c.validate()
    c = Config()
    c.collective_algo_threshold_bytes = -1
    with pytest.raises(ValueError, match="THRESHOLD"):
        c.validate()
    Config().validate()


def test_config_algo_from_env(monkeypatch):
    from horovod_tpu.core.config import Config
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO", "RS_AG")
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO_THRESHOLD", "65536")
    c = Config.from_env()
    assert c.collective_algo == "rs_ag" and c.collective_algo_set
    assert c.collective_algo_threshold_bytes == 65536
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO", "gossip")
    with pytest.raises(ValueError, match="HOROVOD_COLLECTIVE_ALGO"):
        Config.from_env()
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO", "auto")
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO_THRESHOLD", "many")
    with pytest.raises(ValueError, match="THRESHOLD"):
        Config.from_env()


# -- algorithm parity against numpy oracles --------------------------------

@pytest.mark.parametrize("algo", ["direct", "rs_ag", "rhd", "two_level"])
def test_allreduce_algorithms_numerical_parity(hvd, algo):
    from horovod_tpu.ops import collective_ops as co
    n = hvd.size()
    x = _stacked(n, (301,), seed=3)          # odd size exercises padding
    out = np.asarray(co.allreduce(x, hvd.Sum, algo=algo))
    np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)), rtol=2e-5,
                               atol=1e-4)
    avg = np.asarray(co.allreduce(x, hvd.Average, algo=algo))
    np.testing.assert_allclose(avg, np.tile(x.mean(0), (n, 1)), rtol=2e-5,
                               atol=1e-4)


@pytest.mark.parametrize("algo", ["rs_ag", "rhd"])
def test_algorithms_handle_scale_int_and_bool(hvd, algo):
    from horovod_tpu.ops import collective_ops as co
    n = hvd.size()
    # prescale/postscale ride the shared prologue/epilogue
    x = _stacked(n, (64,), seed=4)
    out = np.asarray(co.allreduce(x, hvd.Sum, algo=algo,
                                  prescale_factor=0.5,
                                  postscale_factor=2.0))
    np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)), rtol=1e-5,
                               atol=1e-5)
    # int payload sums exactly
    xi = np.arange(n * 10, dtype=np.int32).reshape(n, 10)
    np.testing.assert_array_equal(
        np.asarray(co.allreduce(xi, hvd.Sum, algo=algo)),
        np.tile(xi.sum(0), (n, 1)))
    # bool goes through the int32 cast prologue
    xb = (np.arange(n * 6).reshape(n, 6) % 2).astype(bool)
    got = np.asarray(co.allreduce(xb, hvd.Sum, algo=algo))
    np.testing.assert_array_equal(got, np.tile(xb.sum(0) > 0, (n, 1)))


def test_forced_algo_via_config_and_counter(hvd):
    import horovod_tpu as hv
    from horovod_tpu.ops import collective_ops as co
    cfg = hv.core.basics.get_config()
    cfg.collective_algo = "rs_ag"
    try:
        n = hvd.size()
        before = _algo_count("rs_ag")
        x = _stacked(n, (32,), seed=5)
        out = np.asarray(co.allreduce(x, hvd.Sum))
        np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)),
                                   rtol=1e-5)
        assert _algo_count("rs_ag") == before + 1
    finally:
        cfg.collective_algo = "auto"


def test_algo_timeline_row_on_change(hvd):
    import horovod_tpu as hv
    from horovod_tpu.ops import collective_ops as co

    class _FakeTl:
        def __init__(self):
            self.instants = []

        def begin(self, *a, **k):
            pass

        def end(self, *a, **k):
            pass

        def instant(self, phase, args=None):
            self.instants.append((phase, args))

    st = hv.core.basics.get_state()
    fake = _FakeTl()
    old = st.timeline
    st.timeline = fake
    try:
        n = hvd.size()
        x = _stacked(n, (16,), seed=6)
        co.allreduce(x, hvd.Sum, algo="direct")
        co.allreduce(x, hvd.Sum, algo="direct")   # steady state: silent
        co.allreduce(x, hvd.Sum, algo="rhd")      # change: one ALGO row
        rows = [a for p, a in fake.instants if p == "ALGO"
                and a["collective"] == "allreduce"]
        assert rows, fake.instants
        flip = rows[-1]
        assert flip["algo"] == "rhd" and flip["prev"] == "direct"
        # exactly one row for the direct->rhd flip (the repeat was silent)
        assert sum(1 for r in rows if r["algo"] == "direct") <= 1
        # per-regime steady state is SILENT: alternating small/large
        # buckets under different per-regime algorithms must not spam
        # a row per step (the dedup key includes the regime)
        cfg = hv.core.basics.get_config()
        cfg.collective_algo_small = "direct"
        cfg.collective_algo_large = "rs_ag"
        cfg.collective_algo_threshold_bytes = 64 * 1024
        try:
            small = _stacked(n, (16,), seed=7)
            large = _stacked(n, (32 * 1024,), seed=8)
            before = len([1 for p, _ in fake.instants if p == "ALGO"])
            for _ in range(3):
                co.allreduce(small, hvd.Sum)
                co.allreduce(large, hvd.Sum)
            after = len([1 for p, _ in fake.instants if p == "ALGO"])
            assert after - before <= 2, fake.instants[before:]
        finally:
            cfg.collective_algo_small = ""
            cfg.collective_algo_large = ""
            cfg.collective_algo_threshold_bytes = 0
    finally:
        st.timeline = old


# -- quantized allgather / reducescatter / alltoall ------------------------

def test_quantized_allgather_roundtrip_and_wire_bar(hvd):
    """Acceptance bar: >=3.5x fewer bytes on the wire than fp32, with
    bounded quantization error."""
    n = hvd.size()
    x = _stacked(n, (2048,), seed=7)
    log0, act0 = _wire_count("logical"), _wire_count("actual")
    out = np.asarray(hvd.quantized_allgather(x))
    exact = np.asarray(hvd.allgather(x))
    assert out.shape == exact.shape
    # each row is the sender's quantized copy: error bounded by the
    # per-block scale (absmax/127)
    np.testing.assert_allclose(out, exact, atol=0.05)
    dlog = _wire_count("logical") - log0
    dact = _wire_count("actual") - act0
    assert dlog == n * 2048 * 4    # each rank's row counted once
    assert dlog / dact >= 3.5, (dlog, dact)


def test_quantized_reducescatter_roundtrip_and_wire_bar(hvd):
    n = hvd.size()
    x = _stacked(n, (n * 512,), seed=8)
    log0, act0 = _wire_count("logical"), _wire_count("actual")
    out = np.asarray(hvd.quantized_reducescatter(x, hvd.Sum))
    exact = np.asarray(hvd.reducescatter(x, hvd.Sum))
    np.testing.assert_allclose(out, exact, atol=0.3)
    dlog = _wire_count("logical") - log0
    dact = _wire_count("actual") - act0
    assert dlog / dact >= 3.5, (dlog, dact)
    # average divides the dequantized fp32 sum
    avg = np.asarray(hvd.quantized_reducescatter(x, hvd.Average))
    np.testing.assert_allclose(
        avg, np.asarray(hvd.reducescatter(x, hvd.Average)), atol=0.05)
    with pytest.raises(ValueError, match="Sum/Average"):
        hvd.quantized_reducescatter(x, hvd.Max)


def test_quantized_alltoall_roundtrip(hvd):
    n = hvd.size()
    x = _stacked(n, (n * 64, 3), seed=9)
    log0, act0 = _wire_count("logical"), _wire_count("actual")
    out = np.asarray(hvd.quantized_alltoall(x))
    exact = np.asarray(hvd.alltoall(x))
    np.testing.assert_allclose(out, exact, atol=0.05)
    assert _wire_count("actual") - act0 < _wire_count("logical") - log0
    with pytest.raises(ValueError, match="divisible"):
        hvd.quantized_alltoall(_stacked(n, (n + 1,), seed=10))


def test_quantized_nonfloat_passes_through_uncompressed(hvd):
    n = hvd.size()
    xi = np.arange(n * 12, dtype=np.int32).reshape(n, 12)
    np.testing.assert_array_equal(np.asarray(hvd.quantized_allgather(xi)),
                                  np.asarray(hvd.allgather(xi)))
    xr = np.arange(n * n * 2, dtype=np.int64).reshape(n, n * 2)
    got = hvd.quantized_reducescatter(xr, hvd.Sum)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(hvd.reducescatter(xr,
                                                               hvd.Sum)))


def test_quantized_dcn_only_routes_two_level(hvd):
    """HOROVOD_COMPRESSION_DCN_ONLY: allgather/reducescatter ride the
    two-level cross.py variants (ICI exact, DCN quantized) when a real
    (cross>1, local>1) hierarchy exists, and stay exact otherwise."""
    import horovod_tpu as hv
    from horovod_tpu.core.mesh import build_hierarchical_mesh
    st = hv.core.basics.get_state()
    cfg = hv.core.basics.get_config()
    n = hvd.size()
    x = _stacked(n, (n * 32,), seed=11)
    exact_ag = np.asarray(hvd.allgather(x))
    exact_rs = np.asarray(hvd.reducescatter(x, hvd.Sum))
    old_hier = st.hier_mesh
    cfg.compression_dcn_only = True
    try:
        # flat hierarchy (cross=1): DCN-only means NO compression
        before = _algo_count("two_level_q8", "allgather")
        out = np.asarray(hvd.quantized_allgather(x))
        np.testing.assert_array_equal(out, exact_ag)
        assert _algo_count("two_level_q8", "allgather") == before
        # real (2, local) hierarchy: quantized cross hop only
        st.hier_mesh = build_hierarchical_mesh(jax.devices(),
                                               local_size=n // 2)
        out = np.asarray(hvd.quantized_allgather(x))
        np.testing.assert_allclose(out, exact_ag, atol=0.05)
        assert _algo_count("two_level_q8", "allgather") == before + 1
        rs = np.asarray(hvd.quantized_reducescatter(x, hvd.Sum))
        np.testing.assert_allclose(rs, exact_rs, atol=0.3)
        assert _algo_count("two_level_q8", "reducescatter") >= 1
        # alltoall has no hierarchical decomposition: exact under
        # DCN-only
        t = _stacked(n, (n * 4,), seed=12)
        np.testing.assert_array_equal(
            np.asarray(hvd.quantized_alltoall(t)),
            np.asarray(hvd.alltoall(t)))
    finally:
        cfg.compression_dcn_only = False
        st.hier_mesh = old_hier


def test_engine_routes_quantized_sharded_state_singles(hvd):
    """With HOROVOD_COMPRESSION=int8 the engine's single-op path moves
    allgather/reducescatter/alltoall payloads over the int8 wire — the
    FSDP/EP sharded-state traffic finally compresses."""
    import horovod_tpu as hv
    eng = hv.core.basics.get_engine()
    cfg = hv.core.basics.get_config()
    cfg.compression = "int8"
    try:
        n = hvd.size()
        x = _stacked(n, (1024,), seed=13)
        log0, act0 = eng.wire_bytes_logical, eng.wire_bytes_actual
        out = np.asarray(hvd.allgather_async(x, name="qag").wait())
        np.testing.assert_allclose(out, np.asarray(hvd.allgather(x)),
                                   atol=0.05)
        dlog = eng.wire_bytes_logical - log0
        dact = eng.wire_bytes_actual - act0
        assert dlog / dact >= 3.5, (dlog, dact)
        r = _stacked(n, (n * 256,), seed=14)
        out = np.asarray(
            hvd.reducescatter_async(r, hvd.Sum, name="qrs").wait())
        np.testing.assert_allclose(
            out, np.asarray(hvd.reducescatter(r, hvd.Sum)), atol=0.3)
        t = _stacked(n, (n * 32,), seed=15)
        out = np.asarray(hvd.alltoall_async(t, name="qa2a").wait())
        np.testing.assert_allclose(out, np.asarray(hvd.alltoall(t)),
                                   atol=0.05)
        # non-float singles stay on the exact path
        xi = np.arange(n * 8, dtype=np.int32).reshape(n, 8)
        out = np.asarray(hvd.allgather_async(xi, name="qagi").wait())
        np.testing.assert_array_equal(out, np.asarray(hvd.allgather(xi)))
    finally:
        cfg.compression = "none"


def test_async_compression_override_and_optout(hvd):
    """Per-call `compression=` on the async sharded-state collectives:
    'int8' forces the quantized wire while the config default is exact,
    and 'none' keeps a payload bit-exact under a config-int8 default
    (the allreduce_async escape hatch, extended)."""
    import horovod_tpu as hv
    eng = hv.core.basics.get_engine()
    cfg = hv.core.basics.get_config()
    n = hvd.size()
    x = _stacked(n, (1024,), seed=30)
    exact = np.asarray(hvd.allgather(x))
    log0, act0 = eng.wire_bytes_logical, eng.wire_bytes_actual
    out = np.asarray(hvd.allgather_async(x, name="force.q",
                                         compression="int8").wait())
    np.testing.assert_allclose(out, exact, atol=0.05)
    assert eng.wire_bytes_actual - act0 < eng.wire_bytes_logical - log0
    cfg.compression = "int8"
    try:
        out = np.asarray(hvd.allgather_async(x, name="opt.out",
                                             compression="none").wait())
        np.testing.assert_array_equal(out, exact)
        r = _stacked(n, (n * 64,), seed=31)
        out = np.asarray(hvd.reducescatter_async(
            r, hvd.Sum, name="opt.out.rs", compression="none").wait())
        np.testing.assert_array_equal(
            out, np.asarray(hvd.reducescatter(r, hvd.Sum)))
    finally:
        cfg.compression = "none"


def test_async_allreduce_explicit_algo_rides_engine(hvd):
    """allreduce_async(algo=...) pins the schedule through the engine
    path (the per-call contract survives the async route)."""
    n = hvd.size()
    x = _stacked(n, (128,), seed=32)
    before = _algo_count("rhd")
    out = np.asarray(hvd.allreduce_async(x, hvd.Sum, name="pin.rhd",
                                         algo="rhd").wait())
    np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)), rtol=1e-5,
                               atol=1e-4)
    assert _algo_count("rhd") == before + 1
    with pytest.raises(ValueError, match="unknown collective algorithm"):
        hvd.allreduce_async(x, hvd.Sum, algo="ring3")
    # an algo request on a single-schedule op is rejected, not dropped
    with pytest.raises(ValueError, match="Sum/Average only"):
        hvd.allreduce_async(x, hvd.Min, algo="rs_ag")
    # explicit algo + explicit int8 wire is a contradiction (the gather
    # transport has no schedule choice) — rejected at enqueue
    with pytest.raises(ValueError, match="conflict"):
        hvd.allreduce_async(x, hvd.Sum, algo="rs_ag", compression="int8")
    # ... while a CONFIG-driven int8 default yields to the explicit
    # schedule (opt-out, exact transport)
    import horovod_tpu as hv
    cfg = hv.core.basics.get_config()
    cfg.compression = "int8"
    try:
        before2 = _algo_count("rs_ag")
        out = np.asarray(hvd.allreduce_async(
            x, hvd.Sum, name="pin.vs.cfg", algo="rs_ag").wait())
        np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)),
                                   rtol=1e-5, atol=1e-4)
        assert _algo_count("rs_ag") == before2 + 1
    finally:
        cfg.compression = "none"
    from horovod_tpu.ops import collective_ops as co
    with pytest.raises(ValueError, match="Sum/Average only"):
        co.allreduce(x, hvd.Max, algo="rhd")
    # transport collectives have no bf16 wire: explicit bf16 is rejected
    # rather than silently ignored
    with pytest.raises(ValueError, match="int8.*none"):
        hvd.allgather_async(x, compression="bf16")


def test_runnable_algorithms_one_home():
    from horovod_tpu.ops import algo
    assert algo.runnable_algorithms(8) == ("direct", "rs_ag", "rhd")
    assert algo.runnable_algorithms(6) == ("direct", "rs_ag")
    assert algo.runnable_algorithms(8, (2, 4)) == \
        ("direct", "rs_ag", "rhd", "two_level")
    # degenerate cross==1 hierarchy: runnable only when forced
    assert "two_level" not in algo.runnable_algorithms(8, (1, 8))
    assert "two_level" in algo.runnable_algorithms(8, (1, 8),
                                                   require_cross=False)
    # hierarchy not covering the world never qualifies
    assert "two_level" not in algo.runnable_algorithms(8, (2, 2))


# -- two-level variants + fail-fast mesh check -----------------------------

def test_two_level_reducescatter_parity_and_wire(hvd):
    from horovod_tpu.core.mesh import build_hierarchical_mesh
    from horovod_tpu.ops.cross import two_level_reducescatter
    n = hvd.size()
    mesh = build_hierarchical_mesh(jax.devices(), local_size=n // 2)
    x = _stacked(n, (n * 16,), seed=16)
    exact = np.asarray(hvd.reducescatter(x, hvd.Sum))
    out = np.asarray(two_level_reducescatter(jnp.asarray(x), hvd.Sum,
                                             mesh))
    np.testing.assert_allclose(out, exact, rtol=1e-5, atol=1e-5)
    q = np.asarray(two_level_reducescatter(jnp.asarray(x), hvd.Sum, mesh,
                                           wire="int8", block_size=32))
    np.testing.assert_allclose(q, exact, atol=0.3)
    b = np.asarray(two_level_reducescatter(jnp.asarray(x), hvd.Sum, mesh,
                                           wire="bf16"))
    np.testing.assert_allclose(b, exact, rtol=0.02, atol=0.2)
    avg = np.asarray(two_level_reducescatter(jnp.asarray(x), hvd.Average,
                                             mesh))
    np.testing.assert_allclose(
        avg, np.asarray(hvd.reducescatter(x, hvd.Average)), rtol=1e-5,
        atol=1e-5)
    # non-float passes through exact regardless of wire
    xi = np.arange(n * n, dtype=np.int32).reshape(n, n)
    qi = np.asarray(two_level_reducescatter(jnp.asarray(xi), hvd.Sum,
                                            mesh, wire="int8"))
    np.testing.assert_array_equal(
        qi, np.asarray(hvd.reducescatter(xi, hvd.Sum)))


def test_two_level_allgather_quantized_cross_hop(hvd):
    from horovod_tpu.core.mesh import build_hierarchical_mesh
    from horovod_tpu.ops.cross import two_level_allgather
    n = hvd.size()
    mesh = build_hierarchical_mesh(jax.devices(), local_size=n // 2)
    x = _stacked(n, (24, 2), seed=17)
    exact = np.asarray(hvd.allgather(x))
    out = np.asarray(two_level_allgather(jnp.asarray(x), mesh))
    np.testing.assert_array_equal(out, exact)
    q = np.asarray(two_level_allgather(jnp.asarray(x), mesh, wire="int8",
                                       block_size=32))
    np.testing.assert_allclose(q, exact, atol=0.05)
    b = np.asarray(two_level_allgather(jnp.asarray(x), mesh, wire="bf16"))
    np.testing.assert_allclose(b, exact, rtol=0.02, atol=0.05)


def test_two_level_fail_fast_on_malformed_mesh(hvd):
    """Satellite: a non-(cross, local) mesh raises a clear ValueError
    instead of an opaque unpack error."""
    from horovod_tpu.ops.cross import (two_level_allgather,
                                       two_level_allreduce,
                                       two_level_reducescatter)
    flat = hvd.core.basics.get_mesh()                 # 1-D ("hvd",)
    n = hvd.size()
    x = jnp.asarray(_stacked(n, (n,), seed=18))
    for fn, args in ((two_level_allreduce, (x, hvd.Sum, flat)),
                     (two_level_allgather, (x, flat)),
                     (two_level_reducescatter, (x, hvd.Sum, flat))):
        with pytest.raises(ValueError, match="2-D .*cross.*local"):
            fn(*args)


# -- in-graph quantized variants -------------------------------------------

def test_inside_quantized_variants_under_shard_map(hvd):
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops import inside
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("hvd",))
    n = hvd.size()

    def run(fn, x):
        f = jax.jit(jax.shard_map(lambda v: fn(v[0])[None], mesh=mesh,
                                  in_specs=(P("hvd"),),
                                  out_specs=P("hvd")))
        return np.asarray(f(jnp.asarray(x)))

    g = _stacked(n, (17,), seed=19)
    out = run(lambda v: inside.quantized_allgather(v, "hvd",
                                                   block_size=16), g)
    np.testing.assert_allclose(out, np.asarray(hvd.allgather(g)),
                               atol=0.05)
    r = _stacked(n, (n * 8,), seed=20)
    out = run(lambda v: inside.quantized_reducescatter(
        v, hvd.Sum, "hvd", block_size=16), r)
    np.testing.assert_allclose(out, np.asarray(hvd.reducescatter(
        r, hvd.Sum)), atol=0.3)
    t = _stacked(n, (n * 2, 3), seed=21)
    out = run(lambda v: inside.quantized_alltoall(v, "hvd",
                                                  block_size=16), t)
    np.testing.assert_allclose(out, np.asarray(hvd.alltoall(t)),
                               atol=0.05)


# -- rank invariance (the PR 1 round-synchronization discipline) -----------

def test_algo_resolution_is_execution_time_not_enqueue_time(hvd):
    """A tuner/config flip between enqueue and the engine cycle must be
    what EXECUTES — resolution reads round-synchronized config on the
    dispatch thread, so all ranks (which share the synced config) run
    the same algorithm for the same bucket."""
    import horovod_tpu as hv
    eng = hv.core.basics.get_engine()
    cfg = hv.core.basics.get_config()
    old_cycle = eng.cycle_time_s
    eng.cycle_time_s = 0.5          # widen the batching window
    try:
        n = hvd.size()
        x = _stacked(n, (64,), seed=22)
        before = _algo_count("rs_ag")
        h = hvd.allreduce_async(x, hvd.Sum, name="flip.bucket")
        # flip AFTER enqueue, before the cycle executes
        cfg.collective_algo = "rs_ag"
        out = np.asarray(h.wait())
        np.testing.assert_allclose(out, np.tile(x.sum(0), (n, 1)),
                                   rtol=1e-5)
        assert _algo_count("rs_ag") == before + 1, \
            "bucket executed with the enqueue-time algorithm"
    finally:
        cfg.collective_algo = "auto"
        eng.cycle_time_s = old_cycle


def test_work_meta_excludes_config_algo(hvd):
    """The negotiation meta must NOT pin the config-driven algorithm at
    enqueue time (only an explicit per-call wire is program identity) —
    the algo travels in the round payload instead, synced from rank 0."""
    import horovod_tpu as hv
    from horovod_tpu.core.types import ReduceOp, RequestType
    from horovod_tpu.ops.engine import Engine, Handle, _Work
    cfg = hv.core.basics.get_config()
    cfg.collective_algo = "rhd"
    try:
        ps = hv.core.basics.get_process_set(None)
        w = _Work(RequestType.ALLREDUCE, "m", np.zeros((hv.size(), 4),
                                                       np.float32),
                  ReduceOp.SUM, ps, Handle("m"))
        meta = Engine._work_meta(w)
        assert "alg" not in meta and "rhd" not in json.dumps(meta)
    finally:
        cfg.collective_algo = "auto"


def test_negotiation_adopts_rank0_algo_plane(hvd):
    """Peers adopt rank 0's collective_algo / per-regime choices each
    round (SynchronizeParameters discipline) — the mechanism that makes
    a mid-flight tuner flip rank-invariant."""
    import horovod_tpu as hv
    eng = hv.core.basics.get_engine()
    cfg = hv.core.basics.get_config()

    class _FakeCoord:
        size, rank = 2, 1

        def bitand(self, probe, tag=""):
            return bytes(32)               # never "all equal"

        def allgather(self, payload, tag=""):
            rank0 = json.loads(payload.decode())
            rank0 = dict(rank0, alg=["rs_ag", "rhd", "rs_ag"], w=[])
            return [json.dumps(rank0).encode(), payload]

    old = (cfg.collective_algo, cfg.collective_algo_small,
           cfg.collective_algo_large)
    try:
        ready, deferred = eng._negotiate(_FakeCoord(), [])
        assert ready == [] and deferred == []
        assert cfg.collective_algo == "rs_ag"
        assert cfg.collective_algo_small == "rhd"
        assert cfg.collective_algo_large == "rs_ag"
    finally:
        (cfg.collective_algo, cfg.collective_algo_small,
         cfg.collective_algo_large) = old


# -- autotuner: per-regime dims + determinism ------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


def _drive_tuner(pm, clock, score_fn, max_cycles=400):
    """Feed the tuner a synthetic (bytes, seconds) trace: each scoring
    window lasts 1 s and moves score_fn(knobs) bytes."""
    sampled = []
    cycles = 0
    while pm.active and cycles < max_cycles:
        nbytes = score_fn(pm)
        for _ in range(pm.steps_per_sample):
            clock.advance(1.0 / pm.steps_per_sample)
            if pm.record(nbytes // pm.steps_per_sample):
                sampled.append(pm._current.copy())
        cycles += 1
    return sampled


def test_tuner_converges_to_different_algos_per_regime():
    """ROADMAP item-1 acceptance: the tuner converges to DIFFERENT
    algorithm choices for small vs large fusion buckets. Synthetic
    deployment truth: rhd wins the latency-bound small regime, rs_ag
    the bandwidth-bound large regime."""
    from horovod_tpu.autotune.tuner import ParameterManager
    clock = _FakeClock()
    pm = ParameterManager(warmup_samples=1, steps_per_sample=1,
                          max_samples=40, seed=0,
                          tune_two_level=False, tune_compression=False,
                          tune_algo=True,
                          algo_choices=("direct", "rs_ag", "rhd"),
                          clock=clock)

    def score(p):
        s = 100.0
        if p.algo_small == "rhd":
            s += 60.0                      # small buckets: latency win
        elif p.algo_small == "rs_ag":
            s -= 10.0
        if p.algo_large == "rs_ag":
            s += 60.0                      # large buckets: bandwidth win
        elif p.algo_large == "rhd":
            s -= 30.0
        return int(s * 1000)

    _drive_tuner(pm, clock, score)
    assert not pm.active, "tuner never pinned"
    assert pm.algo_small == "rhd", pm.algo_small
    assert pm.algo_large == "rs_ag", pm.algo_large
    assert pm.algo_small != pm.algo_large


def test_tuner_deterministic_replay():
    """CI regression: a fixed-seed ParameterManager over a synthetic
    (bytes, seconds) trace reproduces a byte-identical sampled-knob
    sequence — guards the categorical dims against nondeterministic GP
    behavior."""
    from horovod_tpu.autotune.tuner import ParameterManager

    def run():
        clock = _FakeClock()
        pm = ParameterManager(warmup_samples=2, steps_per_sample=3,
                              max_samples=12, seed=7,
                              tune_two_level=True, tune_compression=True,
                              tune_algo=True,
                              algo_choices=("direct", "rs_ag", "rhd"),
                              clock=clock)

        def score(p):
            return int(1000 * (p._current[0] + 10 * p._current[1]))

        sampled = _drive_tuner(pm, clock, score)
        return [s.tobytes() for s in sampled], pm._current.tobytes()

    seq_a, final_a = run()
    seq_b, final_b = run()
    assert len(seq_a) > 5
    assert seq_a == seq_b
    assert final_a == final_b


def test_tuner_algo_dims_frozen_and_snapped():
    from horovod_tpu.autotune.tuner import ParameterManager
    pm = ParameterManager(tune_algo=True,
                          algo_choices=("direct", "rs_ag", "rhd"))
    assert pm.algo_small in ("direct", "rs_ag", "rhd")
    # fusion, cycle, two_level, algo_small, algo_large (compression off)
    assert len(pm._current) == 5
    x = pm._snap(np.array([3.0, 2.0, 0.6, 1.4, 2.0]))
    assert x[3] == 1.0 and x[4] == 2.0
    frozen = ParameterManager(tune_algo=False)
    assert frozen.algo_small == "" and frozen.algo_large == ""
    # a single-choice vocabulary silently freezes (nothing to choose)
    solo = ParameterManager(tune_algo=True, algo_choices=("direct",))
    assert not solo.tune_algo


def test_engine_freezes_algo_dims_on_explicit_env(monkeypatch):
    import horovod_tpu as hv
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_COLLECTIVE_ALGO", "rs_ag")
    hv.shutdown()
    hv.init()
    try:
        eng = hv.core.basics.get_engine()
        assert eng.tuner is not None
        assert not eng.tuner.tune_algo
        n = hv.size()
        x = _stacked(n, (32,), seed=23)
        before = _algo_count("rs_ag")
        out = hv.grouped_allreduce([x], hv.Sum, name="frozen")[0]
        np.testing.assert_allclose(np.asarray(out),
                                   np.tile(x.sum(0), (n, 1)), rtol=1e-5)
        assert _algo_count("rs_ag") == before + 1
    finally:
        hv.shutdown()


def test_engine_autotune_samples_algo_dims(monkeypatch):
    """With HOROVOD_AUTOTUNE=1 (and no explicit algo), the engine's
    tuner carries the per-regime dims and writes sampled choices into
    the live config."""
    import horovod_tpu as hv
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
    hv.shutdown()
    hv.init()
    try:
        eng = hv.core.basics.get_engine()
        assert eng.tuner is not None and eng.tuner.tune_algo
        # world 8, single process: rhd eligible, two_level not (cross=1)
        assert "rhd" in eng.tuner.algo_choices
        assert "two_level" not in eng.tuner.algo_choices
        eng.tuner.max_samples = 2
        n = hv.size()
        x = np.ones((n, 64), np.float32)
        step = 0
        while eng.tuner.active and step < 200:
            hv.synchronize(hv.allreduce_async(x, hv.Sum,
                                              name=f"alg{step}"))
            step += 1
        assert not eng.tuner.active
        import time
        cfg = hv.core.basics.get_config()
        deadline = time.monotonic() + 5.0
        while cfg.collective_algo_small != eng.tuner.algo_small and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert cfg.collective_algo_small == eng.tuner.algo_small
        assert cfg.collective_algo_large == eng.tuner.algo_large
    finally:
        hv.shutdown()


# -- bench + docs presence --------------------------------------------------

def test_bench_has_collectives_sweep():
    import os
    src = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")).read()
    assert "--collectives" in src
    assert "collective_bytes_per_s" in src
    assert "collective_algo_crossover" in src
