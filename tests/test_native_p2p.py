"""P2P TCP ring collectives (native/p2p.py) — the cross-host plane's
wire-optimal transport (the reference's Gloo-ring role,
gloo_operations.cc). Real processes, real sockets, rendezvous over the
native store."""
import uuid

import numpy as np
import pytest


def _ring_worker(kv_port):
    import os
    import numpy as np
    from horovod_tpu.native.p2p import RingComm

    r = int(os.environ["HOROVOD_RANK"])
    n = int(os.environ["HOROVOD_SIZE"])
    c = RingComm("127.0.0.1", kv_port, r, n,
                 prefix=f"t.{os.environ['HOROVOD_JOB_ID']}")
    try:
        # allreduce sum, with a size NOT divisible by the ring (uneven
        # chunk bounds)
        a = np.full(10, float(r + 1), np.float32)
        out = c.allreduce(a, "sum")
        assert np.allclose(out, sum(range(1, n + 1))), out
        # min / max / prod
        assert np.allclose(c.allreduce(a, "min"), 1.0)
        assert np.allclose(c.allreduce(a, "max"), float(n))
        import math
        assert np.allclose(c.allreduce(a, "prod"),
                           float(math.prod(range(1, n + 1))))
        # average flag
        av = c.allreduce(np.full(3, float(r + 1), np.float32), "sum",
                         average=True)
        assert np.allclose(av, (n + 1) / 2), av
        # allgather (2-d payload)
        g = c.allgather(np.full((2, 3), float(r), np.float32))
        assert g.shape == (n, 2, 3)
        for i in range(n):
            assert np.allclose(g[i], float(i)), (i, g[i])
        # broadcast from every root
        for root in range(n):
            b = c.broadcast(
                np.full(5, float(r * 100), np.float32)
                if r == root else np.empty(5, np.float32), root=root)
            assert np.allclose(b, float(root * 100)), (root, b)
        # reducescatter
        rs = c.reducescatter(
            np.arange(2 * n, dtype=np.float32) + r, "sum")
        expect = (np.arange(2 * n, dtype=np.float32) * n
                  + sum(range(n)))
        assert np.allclose(rs, expect[2 * r:2 * r + 2]), rs
        # ragged alltoall via the relay rotation: rows(src->dst) =
        # src + dst, so sizes differ per pair and (0,0) is empty
        chunks = [np.full((r + d, 3), float(10 * r + d), np.float32)
                  for d in range(n)]
        a2a = c.alltoall(chunks)
        for src in range(n):
            assert a2a[src].shape == (src + r, 3), (src, a2a[src].shape)
            assert np.allclose(a2a[src], float(10 * src + r)), a2a[src]
        # barrier (repeat to prove the token ring re-arms)
        for _ in range(3):
            c.barrier()
        # large buffer: crosses the inline/full-duplex threshold
        big = c.allreduce(np.full(1 << 18, 1.0, np.float32), "sum")
        assert np.allclose(big, float(n))
    finally:
        c.close()
    return 1.0


@pytest.mark.parametrize("procs", [2, 4])
def test_ring_collectives(procs):
    from horovod_tpu.native.store import StoreServer
    from horovod_tpu.spark import MultiprocessingJobRunner, run
    server = StoreServer()
    try:
        results = run(_ring_worker, args=(server.port,),
                      num_proc=procs,
                      job_runner=MultiprocessingJobRunner(),
                      env={"HOROVOD_JOB_ID": uuid.uuid4().hex[:8]})
        assert results == [1.0] * procs
    finally:
        server.close()


def _ring_oracle_worker(kv_port):
    """Numerical-parity oracle run (ISSUE 6 satellite): every rank
    re-derives ALL peers' seeded random inputs locally and checks ring
    reducescatter / alltoall / allreduce outputs against exact numpy
    reductions — with sizes that do NOT divide evenly, so the
    `(i * n) // P` uneven chunk-bound walk in allreduce is exercised on
    DISTINCT per-position values (constant fills cannot catch a
    boundary off-by-one)."""
    import os
    import numpy as np
    from horovod_tpu.native.p2p import RingComm

    r = int(os.environ["HOROVOD_RANK"])
    n = int(os.environ["HOROVOD_SIZE"])

    def rows(rank, size, seed_base=100):
        return (np.random.RandomState(seed_base + rank)
                .randn(size).astype(np.float32))

    c = RingComm("127.0.0.1", kv_port, r, n,
                 prefix=f"o.{os.environ['HOROVOD_JOB_ID']}")
    try:
        # allreduce at sizes around the uneven-bound regime: 13 % 4 != 0
        # (bounds 0,3,6,9,13), plus size < P (some empty chunks) and a
        # large non-multiple crossing the inline/full-duplex threshold
        for size in (13, n - 1, (1 << 16) + 7):
            if size <= 0:
                continue
            mine = rows(r, size)
            all_rows = np.stack([rows(i, size) for i in range(n)])
            for op, red in (("sum", np.sum), ("min", np.min),
                            ("max", np.max), ("prod", np.prod)):
                out = c.allreduce(mine, op)
                np.testing.assert_allclose(
                    out, red(all_rows, axis=0), rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(
                c.allreduce(mine, "sum", average=True),
                all_rows.mean(0), rtol=1e-5, atol=1e-5)
        # reducescatter parity (divisible contract) on distinct values
        size = 3 * n
        mine = rows(r, size, seed_base=300)
        all_rows = np.stack([rows(i, size, seed_base=300)
                             for i in range(n)])
        rs = c.reducescatter(mine, "sum")
        cs = size // n
        np.testing.assert_allclose(rs, all_rows.sum(0)[r * cs:(r + 1) * cs],
                                   rtol=1e-5, atol=1e-5)
        # ragged alltoall oracle: rows(src->dst) = (src + 2*dst) % 5,
        # chunk values seeded per (src, dst) so a mis-routed or
        # mis-sliced chunk cannot match
        def chunk(src, dst):
            m = (src + 2 * dst) % 5
            return (np.random.RandomState(1000 + src * n + dst)
                    .randn(m, 2).astype(np.float32))

        out = c.alltoall([chunk(r, d) for d in range(n)])
        for src in range(n):
            np.testing.assert_allclose(out[src], chunk(src, r),
                                       rtol=1e-6, atol=1e-6)
    finally:
        c.close()
    return 1.0


@pytest.mark.parametrize("procs", [3, 4])
def test_ring_oracle_parity_uneven_bounds(procs):
    from horovod_tpu.native.store import StoreServer
    from horovod_tpu.spark import MultiprocessingJobRunner, run
    server = StoreServer()
    try:
        results = run(_ring_oracle_worker, args=(server.port,),
                      num_proc=procs,
                      job_runner=MultiprocessingJobRunner(),
                      env={"HOROVOD_JOB_ID": uuid.uuid4().hex[:8]})
        assert results == [1.0] * procs
    finally:
        server.close()


def test_ring_single_rank_identity():
    from horovod_tpu.native.p2p import RingComm
    c = RingComm("127.0.0.1", 1, 0, 1)
    a = np.arange(4.0, dtype=np.float32)
    assert np.allclose(c.allreduce(a, "sum"), a)
    assert np.allclose(c.broadcast(a), a)
    c.barrier()
    c.close()


def _peer_death_worker(kv_port):
    """Rank 1 dies after the first collective; rank 0's next collective
    must fail fast with P2PError (EOF), not hang for the full timeout."""
    import os
    import time
    import numpy as np
    from horovod_tpu.native.p2p import P2PError, RingComm

    r = int(os.environ["HOROVOD_RANK"])
    c = RingComm("127.0.0.1", kv_port, r, 2,
                 prefix=f"d.{os.environ['HOROVOD_JOB_ID']}", timeout=30)
    out = c.allreduce(np.ones(4, np.float32), "sum")
    assert np.allclose(out, 2.0)
    if r == 1:
        c.close()
        os._exit(0)
    t0 = time.time()
    try:
        c.allreduce(np.ones(1 << 16, np.float32), "sum")
        raise AssertionError("expected P2PError after peer death")
    except P2PError:
        pass
    took = time.time() - t0
    assert took < 20, f"peer-death detection took {took:.1f}s"
    c.close()
    return 1.0


def test_ring_peer_death_fails_fast():
    from horovod_tpu.native.store import StoreServer
    from horovod_tpu.spark import MultiprocessingJobRunner, run
    server = StoreServer()
    try:
        results = run(_peer_death_worker, args=(server.port,),
                      num_proc=2,
                      job_runner=MultiprocessingJobRunner(),
                      env={"HOROVOD_JOB_ID": uuid.uuid4().hex[:8]})
        assert results[0] == 1.0
    finally:
        server.close()


def _star_fallback_worker():
    """HOROVOD_PLANE_P2P=0 must keep the star StoreComm path working."""
    import numpy as np
    from horovod_tpu.interop import _plane
    _plane.init()
    out = _plane.allreduce_np(np.ones(4, np.float32))
    assert out[0] == float(_plane.size())
    # ragged alltoall on the star path (gather-and-pick)
    r, n = _plane.rank(), _plane.size()
    chunks = [np.full((r + d, 2), float(10 * r + d), np.float32)
              for d in range(n)]
    mine = _plane.alltoall_np(chunks)
    for src in range(n):
        assert mine[src].shape == (src + r, 2), (src, mine[src].shape)
        assert np.allclose(mine[src], float(10 * src + r)), mine[src]
    _plane.shutdown()
    return 1.0


def test_plane_p2p_opt_out():
    from horovod_tpu.native.store import StoreServer
    from horovod_tpu.spark import MultiprocessingJobRunner, run
    server = StoreServer()
    try:
        results = run(
            _star_fallback_worker, num_proc=2,
            job_runner=MultiprocessingJobRunner(),
            env={"HOROVOD_INTEROP_FORCE_STORE": "1",
                 "HOROVOD_PLANE_P2P": "0",
                 "HOROVOD_NATIVE_KV_ADDR": "127.0.0.1",
                 "HOROVOD_NATIVE_KV_PORT": str(server.port),
                 "HOROVOD_JOB_ID": uuid.uuid4().hex[:8]})
        assert results == [1.0, 1.0]
    finally:
        server.close()
