"""ISSUE 11 multi-process fleet acceptance (slow tier): REAL replica
worker OS processes behind a ProcessFleetRouter, driven through the
seeded serve-profile plan with ``processes=True`` by the soak harness.

The plan SIGKILLs one worker process mid-traffic, fires a hard
``conn_reset`` plus a seeded ``flaky`` window on surviving replicas'
DISPATCH channels, and drops one admission, while a fresh weight
version is published mid-incident. The bar (docs/serving.md,
process-fleet section):

* the SIGKILLed worker is ejected by the ACCRUAL sweep over real
  heartbeat KV keys within 2 x suspect_s,
* the dispatch blips are absorbed by the retry ladder
  (``hvd_net_retries_total{site="serve.dispatch",outcome="absorbed"}``
  > 0) with ZERO failovers beyond the scheduled kill,
* a replayed dispatch whose reply was severed is served the worker's
  DEDUPED result — answered-exactly-once across the process boundary,
* the victim is RESPAWNED as a fresh process and re-admitted gated on
  the newest published weight version,
* p99 / error-rate SLOs hold outside the bounded recovery windows and
  every shed reply carries retry-after.

Driven through the tools/serve_soak.py --processes CLI so the CLI
contract is covered by the same run. Mirrors test_serve_soak.py,
including the 3-consecutive-green requirement verified at PR time.
"""
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.mark.slow
def test_serve_fleet_soak_acceptance(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_soak.py"),
         "--processes", "--replicas", "2", "--clients", "4",
         "--seed", "7", "--out", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=420)
    assert out.stdout.strip(), out.stderr[-3000:]
    verdict = json.loads(out.stdout)
    detail = json.dumps(verdict, indent=2, sort_keys=True)[:3000]
    assert verdict["processes"] is True, detail
    assert verdict["no_silent_drops"] is True, detail
    assert verdict["answered_once"] is True, detail
    assert verdict["shed_carry_retry_after"] is True, detail
    # the kill: accrual detection over real heartbeat keys, bounded
    assert verdict["failover_bounded"] is True, detail
    assert verdict["failover_s"] <= 2 * verdict["suspect_s"], detail
    # the blips: absorbed by the ladder, ZERO failovers beyond the kill
    assert verdict["blips_absorbed"] is True, detail
    assert verdict["dispatch_absorbed"] > 0, detail
    assert verdict["failovers_only_kills"] is True, detail
    assert verdict["fleet"]["failovers"] == 1, detail
    # the replay: deduped, never a duplicate execution/delivery
    assert verdict["replays_deduped"] is True, detail
    assert verdict["dedupe_hits"] > 0, detail
    # the respawn: fresh process, newest published weights
    assert verdict["respawned_on_newest"] is True, detail
    assert verdict["fleet"]["respawns"] == 1, detail
    assert verdict["capacity_restored"] is True, detail
    assert verdict["slo_held"] is True, detail
    assert verdict["ok"] and out.returncode == 0, detail
    assert (tmp_path / "events.jsonl").exists()
    assert (tmp_path / "requests.jsonl").exists()
    assert (tmp_path / "verdict.json").exists()
