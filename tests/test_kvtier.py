"""Fleet KV tier: eviction ladder, fleet radix index, spill format.

The ISSUE 19 bars (docs/serving.md, fleet-KV-tier section):

* the hvdkv-v1 spill format round-trips byte-identically (atomic
  write, crc32 ledger per leaf + payload crc) and every tier structure
  (HostRing LRU byte bound, DiskTier init scan + token re-verify)
  keeps its contract;
* the prefix cache's eviction hook emits a structured event BEFORE the
  decref (the block is still readable) and evicts LRU
  deepest-refcount-zero-first; a failing hook degrades to plain
  eviction, never an error;
* a demoted run promotes back HBM -> host -> disk bit-identically
  (same tokens as a cold prefill), crc-checked at every hop, with the
  weight-version fence refusing runs demoted under other weights;
* chaos ``kvtier.demote`` / ``kvtier.promote`` corrupt is caught by
  the crc gate before any device byte (re-prefill yields baseline
  tokens); drop degrades to re-prefill, never an error;
* the fleet radix index folds insert/demote/drop/flush events into
  contiguous-from-root lookups with version fencing, and
  ``prefer_holders`` orders candidates deepest-run-first; the
  in-process router builds the index from drained events and routes a
  returning conversation to its holder;
* a cross-replica pull round-trips over the kv_migrate wire shape and
  a corrupted payload is refused by ``unpack_blocks``;
* ``pack_parked`` on a prefix-shared (refcount-held) source stays
  byte-identical under a copy-on-write divergence by another request;
* ``aggregate_healthz`` rolls per-replica prefix-cache TOKEN counts
  into the fleet capacity payload;
* ``tools/kvtier_inspect.py`` lists/shows/verifies spill dirs with
  exit 1 on a crc mismatch, without ever importing jax.
"""
import json
import os
import subprocess
import sys
import time
import zlib
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.chaos import inject
from horovod_tpu.chaos.plan import ChaosPlan
from horovod_tpu.models.gpt import GPT, GPTConfig
from horovod_tpu.serve import (AdmissionQueue, ContinuousBatcher,
                               DiskTier, FleetRadixIndex, FleetRouter,
                               HostRing, Replica, ShardedExecutor,
                               TierEntry, kv_migrate, prefer_holders,
                               read_spill_file)
from horovod_tpu.serve.fleet import aggregate_healthz
from horovod_tpu.serve.kvtier.tier import (spill_file_name,
                                           write_spill_file)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_KW = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
           max_seq_len=48, dtype=jnp.float32, attention_impl="reference")
_BS, _POOL = 4, 32
#: shared "system prompt": 17 tokens = 4 full blocks + 1 partial
_SYS = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2]


@pytest.fixture(autouse=True)
def _disarm():
    inject.uninstall()
    yield
    inject.uninstall()


@pytest.fixture(scope="module")
def gpt():
    train = GPT(GPTConfig(**_KW))
    paged = GPT(GPTConfig(decode=True, **_KW, kv_block_size=_BS,
                          kv_pool_blocks=_POOL))
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    return SimpleNamespace(paged=paged, params=params)


@pytest.fixture(scope="module")
def expool(gpt):
    """One PAGED executor per replica id, shared across batchers (the
    Replica.build discipline — jit caches are the expensive part)."""
    cache = {}

    def get(rid=None):
        if rid not in cache:
            cache[rid] = ShardedExecutor(
                gpt.paged, gpt.params, max_batch=4,
                max_len=_KW["max_seq_len"], replica_id=rid)
        return cache[rid]

    return get


def _batcher(expool, *, rid=None, kv_tier=False, host_mb=1,
             tier_dir=None, kv_crc=True, max_queue=16):
    q = AdmissionQueue(max_queue=max_queue,
                       default_deadline_ms=20000.0, replica_id=rid)
    b = ContinuousBatcher(
        expool(rid), q, buckets=(8, 40), replica_id=rid,
        kv_crc=kv_crc, prefix_cache=True, kv_tier=kv_tier,
        kvtier_host_mb=host_mb,
        kvtier_dir=None if tier_dir is None else str(tier_dir))
    b.warmup()
    return b


def _serve(b, prompt, max_new=4):
    h = b.queue.submit(list(prompt), max_new_tokens=max_new)
    b.run()
    assert h.done() and h.status == "ok", (h.status, h.error)
    return list(h.tokens)


def _evict_all(b):
    """Demote every refcount-zero prefix run down the ladder."""
    b.run()
    while b.prefix.evictable_blocks() > 0:
        assert b.prefix.evict(64) > 0


def _entry(tokens=(1, 2, 3, 4), version=3, fill=b"\x5a"):
    leaf_bytes = [bytes([i]) * 8 + fill * 8 for i in range(2)]
    return TierEntry(tokens, leaf_bytes,
                     [zlib.crc32(x) for x in leaf_bytes],
                     len(tokens), version)


# ---------------------------------------------------------------------------
# spill format + tier structures (jax-free plumbing)
# ---------------------------------------------------------------------------

class TestSpillFormat:
    def test_spill_file_roundtrip(self, tmp_path):
        e = _entry()
        path = str(tmp_path / spill_file_name(e.tokens))
        write_spill_file(path, e, _BS)
        header, payload = read_spill_file(path)
        assert header["format"] == "hvdkv-v1"
        assert header["tokens"] == list(e.tokens)
        assert header["block_size"] == _BS
        assert header["weights_version"] == e.version
        assert header["payload_crc"] == zlib.crc32(payload)
        leaves, off = [], 0
        for n in header["nbytes"]:
            leaves.append(payload[off:off + n])
            off += n
        assert leaves == e.leaf_bytes
        assert e.verify(leaves)
        assert not (tmp_path / (spill_file_name(e.tokens)
                                + ".tmp")).exists()

    def test_verify_catches_a_flip(self):
        e = _entry()
        bad = list(e.leaf_bytes)
        bad[1] = bytes([bad[1][0] ^ 0x01]) + bad[1][1:]
        assert e.verify() and not e.verify(bad)

    def test_disk_tier_scan_and_collision_reverify(self, tmp_path):
        d = DiskTier(str(tmp_path))
        e = _entry()
        assert d.put(e, _BS)
        # a NEW DiskTier over the same root rediscovers membership
        d2 = DiskTier(str(tmp_path))
        assert d2.count() == 1 and d2.contains(e.tokens)
        got = d2.get(e.tokens)
        assert got.leaf_bytes == e.leaf_bytes
        assert got.crcs == e.crcs and got.version == e.version
        # a file-name collision (same crc, different tokens) must be a
        # MISS: get() re-verifies the header token list against the key
        other = (9, 9, 9, 9)
        d2._files[other] = d2._files[e.tokens]
        assert d2.get(other) is None
        d2.pop(e.tokens)
        assert not d2.contains(e.tokens) and d2.count() == 1

    def test_disk_tier_skips_unreadable_files(self, tmp_path):
        (tmp_path / "junk.hvdkv").write_bytes(b"not a spill file")
        d = DiskTier(str(tmp_path))
        assert d.count() == 0

    def test_host_ring_lru_byte_bound(self):
        a = _entry((1,) * 4, fill=b"\xa0")
        b = _entry((2,) * 4, fill=b"\xb0")
        c = _entry((3,) * 4, fill=b"\xc0")
        ring = HostRing(2 * a.nbytes)
        assert ring.put(a) == [] and ring.put(b) == []
        # the bound pushes out the OLDEST entry
        assert ring.put(c) == [a]
        assert ring.get(a.tokens) is None
        # get() refreshes recency: b survives the next overflow
        assert ring.get(b.tokens) is b
        d = _entry((4,) * 4, fill=b"\xd0")
        assert ring.put(d) == [c]
        assert ring.count() == 2 and ring.bytes() == 2 * a.nbytes
        assert ring.pop(b.tokens) is b and ring.pop(b.tokens) is None


# ---------------------------------------------------------------------------
# prefix-cache eviction hook (satellite: structured events, LRU order)
# ---------------------------------------------------------------------------

class TestEvictionHook:
    def test_event_fields_and_pre_decref_ordering(self, expool):
        b = _batcher(expool)
        _serve(b, _SYS + [5, 6])
        captured = []

        def hook(ev):
            # fired BEFORE the decref: the tree still owns the block,
            # so a demotion subscriber can read its device bytes
            assert b.prefix.pool.refcount[ev["block"]] == 1
            captured.append(ev)

        b.prefix.on_evict = hook
        _evict_all(b)
        assert captured, "eviction emitted no events"
        for ev in captured:
            assert set(ev) == {"run", "tokens", "block", "blocks",
                               "token_len"}
            assert len(ev["run"]) == 8 and int(ev["run"], 16) >= 0
            assert ev["token_len"] == len(ev["tokens"])
            assert ev["blocks"] == ev["token_len"] // _BS

    def test_lru_deepest_refcount_zero_first(self, expool):
        b = _batcher(expool)
        t1 = _serve(b, _SYS + [5, 6])
        assert _serve(b, _SYS + [5, 6]) == t1  # shared-prefix reuse
        # a second conversation diverging at block 3 grows a branch
        _serve(b, _SYS[:12] + [9, 10, 11, 12, 13, 14, 15])
        captured = []
        b.prefix.on_evict = captured.append
        _evict_all(b)
        depths = [ev["blocks"] for ev in captured]
        # both branch leaves (depth 4) go before the shared chain,
        # which then cascades leaf-first: 3, 2, 1
        assert depths == [4, 4, 3, 2, 1], depths

    def test_failing_hook_degrades_to_plain_eviction(self, expool):
        b = _batcher(expool)
        _serve(b, _SYS + [5, 6])

        def hook(ev):
            raise RuntimeError("demotion subsystem on fire")

        b.prefix.on_evict = hook
        assert b.prefix.evictable_blocks() > 0
        _evict_all(b)
        assert b.prefix.evictable_blocks() == 0


# ---------------------------------------------------------------------------
# ladder round-trip: HBM -> host / disk -> HBM, bit-identical + fenced
# ---------------------------------------------------------------------------

class TestLadderRoundTrip:
    def _conversation(self, b):
        first = _serve(b, _SYS + [5, 6])
        return _SYS + [5, 6] + first + [7]

    def test_host_rung_bit_identical(self, expool, tmp_path):
        base = _batcher(expool)
        returning = self._conversation(base)
        base_tokens = _serve(base, returning)

        b = _batcher(expool, kv_tier=True, host_mb=1,
                     tier_dir=tmp_path)
        assert self._conversation(b) == returning
        _evict_all(b)
        st = b.kvtier.stats()
        assert st["demoted_blocks"] > 0 and st["host_runs"] > 0
        assert _serve(b, returning) == base_tokens
        st = b.kvtier.stats()
        assert st["promoted_blocks"] >= 4, st
        assert st["corrupt_detected"] == 0

    def test_disk_rung_spills_and_promotes(self, expool, tmp_path):
        base = _batcher(expool)
        returning = self._conversation(base)
        base_tokens = _serve(base, returning)

        # host_mb=0: every demotion overflows the ring straight to disk
        b = _batcher(expool, kv_tier=True, host_mb=0,
                     tier_dir=tmp_path)
        assert self._conversation(b) == returning
        _evict_all(b)
        st = b.kvtier.stats()
        assert st["host_runs"] == 0 and st["disk_runs"] > 0
        spills = [f for f in os.listdir(tmp_path)
                  if f.endswith(".hvdkv")]
        assert len(spills) == st["disk_runs"]
        assert _serve(b, returning) == base_tokens
        assert b.kvtier.stats()["promoted_blocks"] >= 4

    def test_version_fence_refuses_stale_runs(self, expool, tmp_path):
        b = _batcher(expool, rid=5, kv_tier=True, host_mb=1,
                     tier_dir=tmp_path)
        returning = self._conversation(b)
        _evict_all(b)
        held = b.kvtier.stats()["host_runs"]
        assert held > 0
        ex = b.executor
        v0 = ex.params_version
        try:
            ex.params_version = (v0 or 0) + 7
            # the run demoted under v0 must never install under v0+7 —
            # the request re-prefills (params are unchanged, so the
            # tokens still match; only the fence stamp moved)
            _serve(b, returning)
            st = b.kvtier.stats()
            assert st["promoted_blocks"] == 0, st
            assert st["host_runs"] < held  # fenced run was discarded
        finally:
            ex.params_version = v0

    def test_weight_flush_clears_host_tier(self, expool, tmp_path):
        b = _batcher(expool, kv_tier=True, host_mb=1,
                     tier_dir=tmp_path)
        self._conversation(b)
        _evict_all(b)
        assert b.kvtier.stats()["host_runs"] > 0
        b.kvtier.on_flush()
        assert b.kvtier.stats()["host_runs"] == 0
        evs = b.kvtier.drain_events()
        assert {"kind": "flush"} in evs


# ---------------------------------------------------------------------------
# chaos: corrupt caught by the crc gate, drops degrade to re-prefill
# ---------------------------------------------------------------------------

class TestChaos:
    def _baseline(self, expool):
        base = _batcher(expool)
        first = _serve(base, _SYS + [5, 6])
        returning = _SYS + [5, 6] + first + [7]
        return returning, _serve(base, returning)

    def _tiered(self, expool, tmp_path):
        return _batcher(expool, kv_tier=True, host_mb=1,
                        tier_dir=tmp_path)

    def _arm(self, site, kind):
        plan = ChaosPlan.from_dict({"faults": [
            {"rank": 0, "site": site, "kind": kind, "at": 0}]})
        inject.install(plan, rank=0)

    def test_promote_corrupt_caught_before_device(self, expool,
                                                  tmp_path):
        returning, base_tokens = self._baseline(expool)
        b = self._tiered(expool, tmp_path)
        _serve(b, _SYS + [5, 6])
        _evict_all(b)
        self._arm("kvtier.promote", "corrupt")
        assert _serve(b, returning) == base_tokens
        st = b.kvtier.stats()
        assert st["corrupt_detected"] >= 1, st

    def test_demote_corrupt_caught_at_promotion(self, expool,
                                                tmp_path):
        returning, base_tokens = self._baseline(expool)
        b = self._tiered(expool, tmp_path)
        _serve(b, _SYS + [5, 6])
        # the corrupt flips the DEMOTED copy after its crcs are
        # stamped over the clean bytes — only promotion can catch it
        self._arm("kvtier.demote", "corrupt")
        _evict_all(b)
        inject.uninstall()
        assert _serve(b, returning) == base_tokens
        st = b.kvtier.stats()
        assert st["corrupt_detected"] >= 1, st

    def test_drops_degrade_to_reprefill(self, expool, tmp_path):
        returning, base_tokens = self._baseline(expool)
        b = self._tiered(expool, tmp_path)
        _serve(b, _SYS + [5, 6])
        self._arm("kvtier.demote", "drop")
        _evict_all(b)
        inject.uninstall()
        assert b.kvtier.stats()["demote_drops"] == 1
        self._arm("kvtier.promote", "drop")
        assert _serve(b, returning) == base_tokens
        st = b.kvtier.stats()
        assert st["promote_drops"] >= 1, st
        assert st["corrupt_detected"] == 0


# ---------------------------------------------------------------------------
# fleet radix index + candidate ordering (router-side, jax-free)
# ---------------------------------------------------------------------------

class TestFleetIndex:
    def test_apply_events_and_contiguous_lookup(self):
        idx = FleetRadixIndex(_BS)
        run = list(range(1, 9))      # 2 full blocks
        n = idx.apply_events(0, [
            {"kind": "insert", "tokens": run, "version": 1},
            {"kind": "martian", "tokens": run},     # skipped
        ])
        assert n == 1
        assert idx.lookup(run + [77]) == {0: (2, "hbm")}
        # contiguity: a diverging SECOND block caps the match at 1
        assert idx.lookup(run[:4] + [50, 51, 52, 53]) == {0: (1, "hbm")}
        assert idx.lookup([40, 41, 42, 43]) == {}

    def test_demote_drop_flush(self):
        idx = FleetRadixIndex(_BS)
        run = list(range(1, 9))
        idx.apply_events(0, [{"kind": "insert", "tokens": run,
                              "version": 1}])
        idx.apply_events(0, [{"kind": "demote", "tokens": run,
                              "tier": "disk", "version": 1}])
        assert idx.lookup(run) == {0: (2, "disk")}
        idx.apply_events(0, [{"kind": "drop", "tokens": run}])
        assert idx.lookup(run) == {0: (1, "hbm")}
        idx.apply_events(0, [{"kind": "flush"}])
        assert idx.lookup(run) == {}
        assert idx.stats()["events_applied"] == 4

    def test_version_fence(self):
        idx = FleetRadixIndex(_BS)
        run = list(range(1, 9))
        idx.apply_events(0, [{"kind": "insert", "tokens": run,
                              "version": 1}])
        assert idx.lookup(run, versions={0: 1}) == {0: (2, "hbm")}
        assert idx.lookup(run, versions={0: 2}) == {}

    def test_prefer_holders_ordering(self):
        idx = FleetRadixIndex(_BS)
        run = list(range(1, 13))     # 3 full blocks
        idx.note_insert(1, run[:8], "hbm", None)   # shallow, resident
        idx.note_insert(2, run, "hbm", None)       # deep, demoted
        idx.note_tier(2, run, "disk", None)
        idx.note_tier(2, run[:8], "disk", None)
        cands = [SimpleNamespace(id=i) for i in (0, 1, 2)]
        # deepest-first beats tier: a disk holder of MORE blocks wins
        ordered, matched = prefer_holders(cands, run, idx)
        assert [c.id for c in ordered] == [2, 1, 0]
        assert matched == {1: 2, 2: 3}
        # at EQUAL depth the resident (hbm) holder wins the tiebreak
        ordered, _ = prefer_holders(cands, run[:8] + [50] * 4, idx)
        assert [c.id for c in ordered] == [1, 2, 0]
        # no index / no match: the load order is untouched
        assert prefer_holders(cands, run, None) == (cands, {})
        assert prefer_holders(cands, [40] * 8, idx) == (cands, {})
        # min_blocks filters shallow matches out entirely
        _, m = prefer_holders(cands, run, idx, min_blocks=3)
        assert m == {2: 3}


# ---------------------------------------------------------------------------
# cross-replica pull over the kv_migrate wire shape
# ---------------------------------------------------------------------------

class TestCrossReplicaPull:
    def test_export_graft_roundtrip_and_corrupt_refused(
            self, expool, tmp_path):
        src = _batcher(expool, rid=0, kv_tier=True, host_mb=1,
                       tier_dir=tmp_path / "src")
        first = _serve(src, _SYS + [5, 6])
        returning = _SYS + [5, 6] + first + [7]
        _evict_all(src)
        ver = src.executor.params_version
        packed = src.kvtier.export_run(returning, ver)
        assert packed is not None
        header, payload = packed
        assert header["op"] == "kvtier_pull"
        assert len(header["blocks"]) >= 4

        # a flipped payload byte is refused at the unpack gate — it
        # never reaches the destination's install queue
        bad = bytes([payload[0] ^ 0x40]) + payload[1:]
        with pytest.raises(kv_migrate.MigrateCorrupt):
            kv_migrate.unpack_blocks(header, bad)

        dst = _batcher(expool, rid=1, kv_tier=True, host_mb=1,
                       tier_dir=tmp_path / "dst")
        base_tokens = _serve(dst, returning)
        dst.prefix.flush()
        dst.kvtier.on_flush()
        dst.kvtier.submit_graft(header,
                                kv_migrate.unpack_blocks(header,
                                                         payload))
        assert dst.kvtier.has_grafts()
        assert _serve(dst, returning) == base_tokens
        assert dst.kvtier.pulls_in == 1
        assert dst.kvtier.stats()["corrupt_detected"] == 0


# ---------------------------------------------------------------------------
# in-process fleet: index built from heartbeats, returning turn routed
# ---------------------------------------------------------------------------

class TestRouterIntegration:
    def test_router_routes_returning_conversation(self, expool,
                                                  tmp_path):
        reps = [Replica(i, expool(rid=i), buckets=(8, 40),
                        max_queue=32, kv_crc=True, prefix_cache=True,
                        kv_tier=True, kvtier_host_mb=1,
                        kvtier_dir=str(tmp_path / str(i)))
                for i in range(2)]
        router = FleetRouter(reps, interval_s=0.05, suspect_s=5.0)
        router.start()
        try:
            assert router.kvtier_index is not None
            assert router.kvtier_index.block_size == _BS
            h = router.submit(_SYS + [5, 6], max_new_tokens=4)
            assert h.wait(timeout=30) and h.status == "ok"
            first = list(h.tokens)
            # the monitor sweep drains each replica's tier events into
            # the index within one heartbeat interval
            deadline = time.time() + 5
            while time.time() < deadline and \
                    router.kvtier_index.stats()["nodes"] == 0:
                time.sleep(0.05)
            assert router.kvtier_index.stats()["nodes"] > 0
            holders = router.kvtier_index.lookup(_SYS + [5, 6])
            assert holders and all(d >= 4 for d, _t in
                                   holders.values())
            routed0 = router._m_kvtier_routed.value
            h2 = router.submit(_SYS + [5, 6] + first + [7],
                               max_new_tokens=4)
            assert h2.wait(timeout=30) and h2.status == "ok"
            assert router._m_kvtier_routed.value > routed0
            # live healthz rolls the prefix-cache TOKEN counts up
            hz = router.healthz()
            assert hz["capacity"]["prefix_tokens_resident"] > 0
            held = [r for r in hz["replicas"].values()
                    if r.get("prefix_tokens_resident")]
            assert held, hz["replicas"]
        finally:
            router.close()


# ---------------------------------------------------------------------------
# pack_parked on a prefix-shared source under CoW divergence
# ---------------------------------------------------------------------------

class TestPackParkedPrefixCoW:
    def test_parked_source_untouched_by_cow(self, expool):
        b = _batcher(expool)
        P = list(range(1, 11))       # 10 tokens = 2 full blocks + 2
        _serve(b, P, max_new=2)      # P's full blocks enter the tree
        h = b.queue.submit(P, max_new_tokens=1, hold_kv=True)
        b.run()
        assert h.status == "ok"      # parked, blocks shared with tree
        hdr1, pay1 = kv_migrate.pack_parked(
            b, h.rid, fid="cow0", max_new_tokens=4,
            deadline_ms=20000.0)
        # a divergence INSIDE the parked row's shared block 1 must CoW
        # into a fresh block, never mutate the refcount-held source
        _serve(b, P[:6] + [60, 61, 62, 63], max_new=2)
        hdr2, pay2 = kv_migrate.pack_parked(
            b, h.rid, fid="cow1", max_new_tokens=4,
            deadline_ms=20000.0)
        assert pay1 == pay2
        assert [blk["crcs"] for blk in hdr1["blocks"]] == \
               [blk["crcs"] for blk in hdr2["blocks"]]
        b.release_parked(h.rid)
        b.run()


# ---------------------------------------------------------------------------
# healthz token rollup (satellite: fleet capacity payload)
# ---------------------------------------------------------------------------

class TestHealthzTokens:
    def test_aggregate_rolls_up_prefix_token_counts(self):
        info = {
            0: {"state": "up", "up": True, "draining": False,
                "queue_depth": 0, "weights_version": 1, "restarts": 0,
                "queue_free": 4, "kv_blocks_total": 32,
                "kv_blocks_in_use": 2,
                "prefix_tokens_resident": 40,
                "prefix_tokens_evictable": 24},
            1: {"state": "up", "up": True, "draining": False,
                "queue_depth": 0, "weights_version": 1, "restarts": 0,
                "queue_free": 4, "kv_blocks_total": 32,
                "kv_blocks_in_use": 0,
                "prefix_tokens_resident": 8,
                "prefix_tokens_evictable": 8},
            2: {"state": "up", "up": True, "draining": False,
                "queue_depth": 0, "weights_version": 1, "restarts": 0,
                "queue_free": 4},   # slotted replica: no prefix cache
        }
        out = aggregate_healthz(info, draining=False,
                                retry_after_ms=100.0)
        cap = out["capacity"]
        assert cap["prefix_tokens_resident"] == 48
        assert cap["prefix_tokens_evictable"] == 32
        assert out["replicas"]["0"]["prefix_tokens_resident"] == 40
        assert out["replicas"]["1"]["prefix_tokens_evictable"] == 8
        assert "prefix_tokens_resident" not in out["replicas"]["2"]


# ---------------------------------------------------------------------------
# inspect CLI (satellite: stdlib-only, crc exit code, never imports jax)
# ---------------------------------------------------------------------------

class TestInspectTool:
    TOOL = os.path.join(REPO, "tools", "kvtier_inspect.py")

    def _spill_dir(self, tmp_path):
        d = DiskTier(str(tmp_path))
        assert d.put(_entry((1, 2, 3, 4), fill=b"\xa1"), _BS)
        assert d.put(_entry((1, 2, 3, 4, 5, 6, 7, 8), fill=b"\xb2"),
                     _BS)
        return sorted(f for f in os.listdir(tmp_path)
                      if f.endswith(".hvdkv"))

    def _run(self, *args):
        return subprocess.run([sys.executable, self.TOOL, *args],
                              capture_output=True, text=True,
                              timeout=60)

    def test_list_show_verify_clean(self, tmp_path):
        names = self._spill_dir(tmp_path)
        out = self._run("list", str(tmp_path))
        assert out.returncode == 0, out.stderr
        assert "2 spill file(s)" in out.stdout
        out = self._run("show", str(tmp_path), names[0])
        assert out.returncode == 0 and "hvdkv-v1" in out.stdout
        out = self._run("verify", str(tmp_path))
        assert out.returncode == 0 and "OK" in out.stdout

    def test_verify_exits_1_on_crc_mismatch(self, tmp_path):
        names = self._spill_dir(tmp_path)
        p = tmp_path / names[0]
        raw = bytearray(p.read_bytes())
        raw[-1] ^= 0xAA              # flip one payload byte
        p.write_bytes(bytes(raw))
        out = self._run("verify", str(tmp_path))
        assert out.returncode == 1, out.stdout
        assert "CORRUPT" in out.stdout and "crc32" in out.stdout

    def test_tool_does_not_import_jax(self, tmp_path):
        """The inspect CLI must stay deployable on hosts without a jax
        install (the ckpt_inspect contract, applied to the tier)."""
        self._spill_dir(tmp_path)
        code = ("import sys; sys.modules['jax'] = None\n"
                "import runpy; sys.argv = ['kvtier_inspect', "
                f"'verify', {str(tmp_path)!r}]\n"
                f"runpy.run_path({self.TOOL!r}, "
                "run_name='__main__')\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=60)
        assert "OK" in out.stdout, (out.stdout, out.stderr)


# ---------------------------------------------------------------------------
# soak acceptance (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_kvtier_soak_acceptance(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_soak.py"),
         "--kv-tier", "--seed", "7", "--out", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.stdout.strip(), out.stderr[-3000:]
    verdict = json.loads(out.stdout)
    detail = json.dumps(verdict, indent=2, sort_keys=True)[:3000]
    assert verdict["ok"] is True, detail
    assert out.returncode == 0
