"""Multi-process serve fleet (ISSUE 11), tier-1 bars — everything that
can be proven WITHOUT spawning worker processes (the real-process
acceptance lives in test_serve_fleet_soak.py, slow tier):

* the framed dispatch protocol classifies faults through the
  resilience plane (EOF/reset -> Retryable DispatchConnError; garbage
  and timeouts fatal);
* the worker endpoint's fid dedupe serves a REPLAYED dispatch its
  cached (or in-flight) result — a severed reply never becomes a
  duplicate execution;
* the router's dispatch ladder absorbs conn_reset/flaky blips with
  ZERO failovers, and the replay after a post-send sever is served the
  deduped result;
* dispatch failure to the last replica resolves the handle as a
  structured rejection with a capacity-scaled retry_after — never a
  silent drop or a hang;
* Retry-After rounding is a TRUE ceiling (2000 ms -> 2 s, boundary
  values asserted);
* the aggregate fleet /healthz reports per-replica state + live
  capacity, 200 while capacity exists, 503 at zero;
* drain() racing a replica respawn resolves every in-flight request
  exactly once and never re-admits after the drain;
* the processes=True serve plan composition is seed-deterministic,
  epoch-pins the kill, and fail-fast validates its sites;
* evaluate_fleet goes red on each process-boundary invariant.
"""
import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.chaos import inject
from horovod_tpu.chaos.plan import ChaosPlan, PlanError, random_plan
from horovod_tpu.models.gpt import GPT, GPTConfig
from horovod_tpu.native import resilience
from horovod_tpu.native.store import StoreServer
from horovod_tpu.obs import metrics as obs_metrics
from horovod_tpu.serve import (AdmissionQueue, ContinuousBatcher,
                               FleetRouter, Rejected, Replica,
                               ShardedExecutor, make_fleet_server,
                               retry_after_seconds, wire)
from horovod_tpu.serve.proc_fleet import ProcessFleetRouter
from horovod_tpu.serve.soak import evaluate_fleet
from horovod_tpu.serve.worker import ReplicaEndpoint

_KW = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
           max_seq_len=48, dtype=jnp.float32, attention_impl="reference")


@pytest.fixture(autouse=True)
def _disarm():
    inject.uninstall()
    yield
    inject.uninstall()


@pytest.fixture(scope="module")
def gpt():
    train = GPT(GPTConfig(**_KW))
    dec = GPT(GPTConfig(decode=True, **_KW))
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    return SimpleNamespace(dec=dec, params=params)


@pytest.fixture(scope="module")
def expool(gpt):
    cache = {}

    def get(rid=None, max_batch=4):
        key = (rid, max_batch)
        if key not in cache:
            cache[key] = ShardedExecutor(
                gpt.dec, gpt.params, max_batch=max_batch,
                max_len=_KW["max_seq_len"], replica_id=rid)
        return cache[key]

    return get


def _stack(expool, rid=0, *, max_queue=32, deadline_ms=8000.0,
           start=True):
    """An in-thread replica: executor + queue + batcher + endpoint."""
    q = AdmissionQueue(max_queue=max_queue,
                       default_deadline_ms=deadline_ms, replica_id=rid)
    b = ContinuousBatcher(expool(rid=rid), q, buckets=(8,),
                          replica_id=rid, kv_crc=False, spec_k=0,
                          prefix_cache=False)
    b.warmup()
    if start:
        b.start()
    ep = ReplicaEndpoint(b, rid=rid).start()
    return SimpleNamespace(queue=q, batcher=b, ep=ep)


def _rpc(addr, fid, prompt, max_new=4, deadline_ms=8000):
    s = wire.connect(addr, timeout=2.0)
    try:
        wire.send_msg(s, {"op": "submit", "fid": fid, "prompt": prompt,
                          "max_new_tokens": max_new,
                          "deadline_ms": deadline_ms})
        ack = wire.recv_msg(s, timeout=5.0)
        if ack.get("ack") != "accepted":
            return ack, None
        return ack, wire.recv_msg(s, timeout=20.0)
    finally:
        s.close()


# ---------------------------------------------------------------------------
# Retry-After: a true ceiling
# ---------------------------------------------------------------------------

class TestRetryAfterCeiling:
    @pytest.mark.parametrize("ms,expect", [
        (1, 1), (999, 1), (1000, 1), (1000.5, 2), (1999, 2),
        (2000, 2),          # the old int(ms/1000)+1 said 3 here
        (2000.5, 3), (2001, 3), (60000, 60), (0.5, 1),
    ])
    def test_boundaries(self, ms, expect):
        assert retry_after_seconds(ms) == expect

    def test_never_zero(self):
        # a sub-second hint must not become an immediate retry
        assert retry_after_seconds(0.001) == 1


# ---------------------------------------------------------------------------
# wire protocol: classification through the resilience plane
# ---------------------------------------------------------------------------

class TestWire:
    def test_roundtrip(self):
        a, b = socket.socketpair()
        try:
            wire.send_msg(a, {"op": "submit", "tokens": [1, 2, 3]})
            got = wire.recv_msg(b, timeout=2.0)
            assert got == {"op": "submit", "tokens": [1, 2, 3]}
        finally:
            a.close()
            b.close()

    def test_eof_mid_frame_is_retryable(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", 100) + b'{"par')
            a.close()
            with pytest.raises(wire.DispatchConnError) as ei:
                wire.recv_msg(b, timeout=2.0)
            assert resilience.is_retryable(ei.value)
        finally:
            b.close()

    def test_oversized_frame_is_fatal(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", wire.MAX_FRAME_BYTES + 1))
            with pytest.raises(wire.DispatchError) as ei:
                wire.recv_msg(b, timeout=2.0)
            assert not resilience.is_retryable(ei.value)
        finally:
            a.close()
            b.close()

    def test_refused_dial_is_retryable(self):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            free = probe.getsockname()[1]
        with pytest.raises(wire.DispatchConnError) as ei:
            wire.connect(("127.0.0.1", free), timeout=0.5)
        assert resilience.is_retryable(ei.value)


# ---------------------------------------------------------------------------
# worker endpoint: fid replay dedupe across the process boundary
# ---------------------------------------------------------------------------

class TestEndpointDedupe:
    def test_replay_served_cached_result(self, expool):
        st = _stack(expool)
        try:
            ack, r1 = _rpc(st.ep.address, "f.1", [1, 2, 3])
            assert ack["ack"] == "accepted" and r1["status"] == "ok"
            assert len(r1["tokens"]) == 4
            ack, r2 = _rpc(st.ep.address, "f.1", [1, 2, 3])
            assert r2 == r1
            assert st.ep.dedupe_hits == 1
            # a fresh fid is NOT deduped
            _, r3 = _rpc(st.ep.address, "f.2", [1, 2, 3])
            assert r3["tokens"] == r1["tokens"]   # greedy, same prompt
            assert st.ep.dedupe_hits == 1
        finally:
            st.batcher.stop()
            st.ep.close()

    def test_severed_reply_replay_not_executed_twice(self, expool):
        """The conn_reset scenario: the request frame lands, the
        socket dies before the reply — the replay must be served the
        SAME result and the queue must have admitted exactly once."""
        st = _stack(expool)
        try:
            admitted0 = st.queue.admitted_count
            s = wire.connect(st.ep.address, timeout=2.0)
            wire.send_msg(s, {"op": "submit", "fid": "sever.1",
                              "prompt": [5, 6], "max_new_tokens": 3,
                              "deadline_ms": 8000})
            time.sleep(0.05)
            s.close()                      # the reply is lost
            deadline = time.monotonic() + 5.0
            while st.queue.admitted_count == admitted0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            ack, r = _rpc(st.ep.address, "sever.1", [5, 6], max_new=3)
            assert ack["ack"] == "accepted"
            assert r["status"] == "ok" and len(r["tokens"]) == 3
            assert st.ep.dedupe_hits == 1
            # executed ONCE: the replay joined, it did not re-enqueue
            assert st.queue.admitted_count == admitted0 + 1
        finally:
            st.batcher.stop()
            st.ep.close()

    def test_queue_full_and_draining_acks(self, expool):
        st = _stack(expool, rid=1, max_queue=2, start=False)
        try:
            st.queue.submit([1, 2], max_new_tokens=2)
            st.queue.submit([3, 4], max_new_tokens=2)
            ack, _ = _rpc(st.ep.address, "full.1", [5, 6])
            assert ack["ack"] == "rejected"
            assert (ack["retry_after_ms"] or 0) > 0
            st.batcher.draining = True
            ack, _ = _rpc(st.ep.address, "drain.1", [5, 6])
            assert ack["ack"] == "rejected"
            assert "draining" in ack["reason"]
            assert (ack["retry_after_ms"] or 0) > 0
        finally:
            st.ep.close()


# ---------------------------------------------------------------------------
# dispatch ladder: blips absorb, failures shed structurally
# ---------------------------------------------------------------------------

def _absorbed() -> int:
    return int(obs_metrics.get_registry().counter(
        "hvd_net_retries_total", resilience.RETRIES_HELP,
        {"site": "serve.dispatch", "outcome": "absorbed"}).value)


def _wire_router(srv, ep_addr, *, n=1, deadline_ms=8000.0):
    """A ProcessFleetRouter pointed at an IN-THREAD endpoint: no
    process spawn, same dispatch path (ladder, chaos gate, dedupe)."""
    router = ProcessFleetRouter(
        n, kv_addr="127.0.0.1", kv_port=srv.port,
        worker={"deadline_ms": deadline_ms, "max_queue": 32})
    for rid, rep in router.replicas.items():
        rep.state = "up"
        rep.addr = ep_addr if rid == 0 else None
    router.started = True
    return router


class TestDispatchLadder:
    def test_conn_reset_absorbed_and_replay_deduped(self, expool):
        st = _stack(expool, rid=2)
        srv = StoreServer()
        router = _wire_router(srv, st.ep.address)
        try:
            inject.install(ChaosPlan.from_dict({"faults": [
                {"rank": 0, "site": "serve.dispatch",
                 "kind": "conn_reset", "peer": 0, "at": 0}]}), rank=0)
            before = _absorbed()
            h = router.submit([1, 2, 3], max_new_tokens=4)
            assert h.wait(15.0) and h.status == "ok"
            assert len(h.tokens) == 4
            assert h.resolutions == 1
            # the blip was ABSORBED: >=1 ladder retry, ZERO failovers,
            # and the replay was served the worker's deduped result
            assert _absorbed() > before
            assert router.stats()["failovers"] == 0
            assert st.ep.dedupe_hits == 1
        finally:
            router._kv.close()
            st.batcher.stop()
            st.ep.close()
            srv.close()

    def test_flaky_window_absorbed(self, expool):
        st = _stack(expool, rid=3)
        srv = StoreServer()
        router = _wire_router(srv, st.ep.address)
        try:
            # prob=1.0 drops every crossing of [0, 1]: attempts 0 and 1
            # drop deterministically, attempt 2 exits the window
            inject.install(ChaosPlan.from_dict({"faults": [
                {"rank": 0, "site": "serve.dispatch", "kind": "flaky",
                 "peer": 0, "prob": 1.0, "after": 0, "until": 1}]}),
                rank=0)
            before = _absorbed()
            h = router.submit([4, 5], max_new_tokens=2)
            assert h.wait(15.0) and h.status == "ok"
            assert _absorbed() >= before + 2
            assert router.stats()["failovers"] == 0
        finally:
            router._kv.close()
            st.batcher.stop()
            st.ep.close()
            srv.close()

    def test_dead_endpoint_sheds_with_scaled_retry_after(self, expool):
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            free = probe.getsockname()[1]
        srv = StoreServer()
        router = _wire_router(srv, ("127.0.0.1", free))
        router._ladder = resilience.RetryPolicy(
            retries=1, backoff_base_ms=5.0, budget_s=0.5)
        try:
            h = router.submit([1, 2], max_new_tokens=2)
            assert h.wait(10.0), "handle must never hang"
            assert h.status == "rejected"
            assert h.resolutions == 1
            assert (h.retry_after_ms or 0) > 0
        finally:
            router._kv.close()
            srv.close()

    def test_zero_capacity_sheds_synchronously(self, expool):
        srv = StoreServer()
        router = _wire_router(srv, None)
        router.replicas[0].state = "down"
        try:
            with pytest.raises(Rejected) as ei:
                router.submit([1, 2])
            assert (ei.value.retry_after_ms or 0) > 0
        finally:
            router._kv.close()
            srv.close()


# ---------------------------------------------------------------------------
# aggregate fleet /healthz + front door
# ---------------------------------------------------------------------------

class TestFleetHealthz:
    def test_aggregate_and_zero_capacity_503(self, expool):
        reps = [Replica(i, expool(rid=i), buckets=(8,), max_queue=8,
                        kv_crc=False)
                for i in range(2)]
        router = FleetRouter(reps, interval_s=0.1, suspect_s=0.5,
                             auto_restart=False)
        router.start()
        srv = make_fleet_server(router)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                info = json.loads(r.read())
                assert r.status == 200
            assert info["ok"] is True
            assert info["capacity"]["replicas_up"] == 2
            assert info["capacity"]["queue_free"] > 0
            assert set(info["replicas"]) == {"0", "1"}
            assert all(v["state"] == "up"
                       for v in info["replicas"].values())
            # the front door routes: one generate through the fleet
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"tokens": [1, 2, 3],
                                 "max_new_tokens": 2}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=15) as r:
                out = json.loads(r.read())
            assert out["status"] == "ok" and len(out["tokens"]) == 2
            # zero live capacity -> 503, same payload shape
            for rep in reps:
                rep.batcher.stop()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5)
            assert ei.value.code == 503
            info = json.loads(ei.value.read())
            assert info["ok"] is False
            assert info["capacity"]["replicas_up"] == 0
        finally:
            srv.shutdown()
            router.close()

    def test_process_router_healthz_shape(self, expool):
        srv = StoreServer()
        router = _wire_router(srv, None, n=2)
        router.replicas[1].state = "respawning"
        try:
            info = router.healthz()
            assert info["replicas"]["1"]["state"] == "respawning"
            assert info["capacity"]["replicas_total"] == 2
            assert info["retry_after_ms"] > 0
        finally:
            router._kv.close()
            srv.close()


# ---------------------------------------------------------------------------
# drain racing a respawn (satellite): exactly-once, no zombie re-admit
# ---------------------------------------------------------------------------

class TestDrainDuringRespawn:
    def test_drain_racing_recover_resolves_every_handle(self, expool):
        reps = [Replica(i, expool(rid=i), buckets=(8,), max_queue=32,
                        kv_crc=False)
                for i in range(2)]
        router = FleetRouter(reps, interval_s=0.05, suspect_s=0.2,
                             auto_restart=True, rewarm_timeout_s=5.0)
        router.start()
        handles = []
        # slow the victim's rebuild so drain() lands MID-respawn
        victim = reps[0]
        orig_build = victim.build

        def slow_build():
            time.sleep(0.4)
            orig_build()

        victim.build = slow_build
        try:
            # keep a tail of work in flight on both replicas
            rng = np.random.RandomState(3)
            for _ in range(8):
                handles.append(router.submit(
                    list(rng.randint(1, 64, 4)), max_new_tokens=24))
            # kill the victim's scheduler: eject + auto-restart begin
            victim.batcher._dead = True
            deadline = time.monotonic() + 5.0
            while victim.state != "down" and not router._restarting:
                assert time.monotonic() < deadline, "never ejected"
                time.sleep(0.01)
            router.drain(timeout_s=5.0)
            # EVERY handle resolved exactly once, never silently
            for h in handles:
                assert h.done(), "drain left a handle unresolved"
                assert h.resolutions <= 1
                assert h.status in ("ok", "expired", "rejected")
                if h.status == "rejected":
                    assert (h.retry_after_ms or 0) > 0
            # and the respawn did NOT re-admit into a drained fleet
            time.sleep(0.8)   # let the slow recover thread finish
            assert victim.state != "up"
        finally:
            victim.build = orig_build
            router.close()


# ---------------------------------------------------------------------------
# plan composition + verdict reds
# ---------------------------------------------------------------------------

class TestProcessPlan:
    def test_deterministic_and_composed(self):
        p1 = random_plan(7, 2, 240, profile="serve", processes=True)
        p2 = random_plan(7, 2, 240, profile="serve", processes=True)
        assert p1.to_json() == p2.to_json()
        sites = {(f.site, f.kind) for f in p1.faults}
        assert ("serve.proc", "crash") in sites
        assert ("serve.dispatch", "conn_reset") in sites
        assert ("serve.dispatch", "flaky") in sites
        assert ("serve.admit", "drop") in sites
        kill = next(f for f in p1.faults if f.kind == "crash")
        # epoch-pinned: the respawned worker's fresh counters must not
        # re-fire the kill every generation
        assert kill.epoch == 0
        # blips never target the victim (nothing to absorb INTO)
        for f in p1.faults:
            if f.site == "serve.dispatch":
                assert f.peer != kill.peer

    def test_fail_fast_validation(self):
        with pytest.raises(PlanError):
            random_plan(7, 4, 40, profile="train", processes=True)
        with pytest.raises(PlanError):
            ChaosPlan.from_dict({"faults": [
                {"rank": 0, "site": "serve.dispatch", "kind": "crash",
                 "peer": 0, "at": 1}]})
        with pytest.raises(PlanError):
            ChaosPlan.from_dict({"faults": [
                {"rank": 0, "site": "serve.proc", "kind": "conn_reset",
                 "peer": 0, "at": 1}]})
        # the new sites accept their kinds
        ChaosPlan.from_dict({"faults": [
            {"rank": 0, "site": "serve.proc", "kind": "crash",
             "peer": 1, "at": 5, "epoch": 0},
            {"rank": 0, "site": "serve.proc", "kind": "slow_rank",
             "peer": 0, "at": 3, "seconds": 1.5},
            {"rank": 0, "site": "serve.dispatch", "kind": "conn_reset",
             "peer": 1, "at": 2},
            {"rank": 0, "site": "serve.dispatch", "kind": "flaky",
             "peer": 1, "prob": 0.5, "after": 1, "until": 4},
            {"rank": 0, "site": "serve.dispatch", "kind": "jitter",
             "peer": 1, "seconds": 0.05, "after": 0, "until": 9},
        ]})


def _green_fixture():
    plan = random_plan(7, 2, 240, profile="serve", processes=True)
    kill = next(f for f in plan.faults if f.kind == "crash")
    victim = kill.peer
    records = [{"fid": i, "t0": 1.0 + i, "t1": 1.05 + i,
                "status": "ok", "latency_ms": 50.0,
                "retry_after_ms": None, "resolutions": 1}
               for i in range(30)]
    events = [
        {"kind": "chaos", "fault": "crash", "site": "serve.proc",
         "peer": victim, "t": 100.0},
        {"kind": "fleet", "event": "eject", "replica": victim,
         "t": 101.0},
        {"kind": "fleet", "event": "readmit", "replica": victim,
         "weights_version": 2, "t": 108.0},
    ]
    fleet_stats = {
        "replicas_up": 2, "inflight": 0, "failovers": 1,
        "respawns": 1, "duplicates_suppressed": 0,
        "replicas": {0: {"weights_version": 2},
                     1: {"weights_version": 2}},
    }
    return plan, records, events, fleet_stats


def _eval(plan, records, events, fleet_stats, **kw):
    base = dict(replicas=2, suspect_s=1.0, slo_p99_ms=15000.0,
                slo_error_rate=0.02, recovery_window_s=6.0,
                newest_version=2, dispatch_absorbed=3, dedupe_hits=1)
    base.update(kw)
    return evaluate_fleet(records, events, plan, fleet_stats, **base)


class TestFleetVerdict:
    def test_green(self):
        v = _eval(*_green_fixture())
        assert v["blips_absorbed"] is True
        assert v["failovers_only_kills"] is True
        assert v["replays_deduped"] is True
        assert v["respawned_on_newest"] is True
        assert v["ok"] is True, json.dumps(v, indent=2, default=str)

    def test_red_blip_not_absorbed(self):
        v = _eval(*_green_fixture(), dispatch_absorbed=0)
        assert v["blips_absorbed"] is False and v["ok"] is False

    def test_red_replay_not_deduped(self):
        v = _eval(*_green_fixture(), dedupe_hits=0)
        assert v["replays_deduped"] is False and v["ok"] is False

    def test_red_blip_caused_failover(self):
        plan, records, events, stats = _green_fixture()
        stats = dict(stats, failovers=2)
        v = _eval(plan, records, events, stats)
        assert v["failovers_only_kills"] is False and v["ok"] is False

    def test_red_respawn_on_stale_weights(self):
        plan, records, events, stats = _green_fixture()
        events = [dict(e) for e in events]
        for e in events:
            if e.get("event") == "readmit":
                e["weights_version"] = 1
        v = _eval(plan, records, events, stats)
        assert v["respawned_on_newest"] is False and v["ok"] is False

    def test_red_unbounded_failover(self):
        plan, records, events, stats = _green_fixture()
        events = [dict(e) for e in events]
        for e in events:
            if e.get("event") == "eject":
                e["t"] = 103.5          # 3.5s > 2 x suspect_s
        v = _eval(plan, records, events, stats)
        assert v["failover_bounded"] is False and v["ok"] is False
