"""Model-family tests: ViT (image encoder) and MoE-GPT (expert-parallel LM).

ViT and MoE extend the model zoo beyond ResNet/GPT; the MoE tests exercise
the ep-axis all_to_all dispatch (parallel/ep.py) end to end through a real
GSPMD train step — the strategy the reference only provides primitives for
(SURVEY §2.6)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.moe import (MoEGPT, MoEGPTConfig, moe_aux_loss,
                                    moe_partition_rules)
from horovod_tpu.models.vit import ViT_Tiny, ViTConfig, ViT, \
    vit_partition_rules
from horovod_tpu.parallel.mesh_utils import make_mesh
from horovod_tpu.parallel.tp import shard_params


class TestViT:
    def _tiny(self, **kw):
        kw.setdefault("attention_impl", "reference")
        return ViT_Tiny(num_classes=10, dtype=jnp.float32, **kw)

    def test_forward_shape_finite(self):
        model = self._tiny()
        imgs = jnp.asarray(np.random.RandomState(0).rand(2, 32, 32, 3),
                           jnp.float32)
        v = model.init(jax.random.PRNGKey(0), imgs)
        out = model.apply(v, imgs)
        assert out.shape == (2, 10)
        assert np.isfinite(np.asarray(out)).all()

    def test_cls_pool_matches_shape(self):
        cfg = ViTConfig(image_size=32, patch_size=8, num_classes=5,
                        num_layers=1, num_heads=2, head_dim=8, pool="cls",
                        dtype=jnp.float32, attention_impl="reference")
        model = ViT(cfg)
        imgs = jnp.zeros((3, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs)
        assert model.apply(v, imgs).shape == (3, 5)

    def test_dp_train_step_learns(self, hvd):
        from horovod_tpu.training import (init_replicated, make_train_step,
                                          shard_batch)
        mesh = hvd.core.basics.get_mesh()
        model = self._tiny()
        r = np.random.RandomState(0)
        imgs = r.rand(16, 32, 32, 3).astype(np.float32)
        lbls = r.randint(0, 10, (16,)).astype(np.int32)
        v = model.init(jax.random.PRNGKey(0), jnp.asarray(imgs[:1]))
        params = init_replicated(v["params"], mesh)
        tx = optax.adam(1e-3)
        step = make_train_step(model.apply, tx, mesh)
        opt = init_replicated(step.init_opt_state(params), mesh)
        xi, yi = shard_batch(imgs, mesh), shard_batch(lbls, mesh)
        params, opt, _, l1 = step(params, opt, {}, xi, yi)
        for _ in range(3):
            params, opt, _, l2 = step(params, opt, {}, xi, yi)
        assert float(l2) < float(l1)

    def test_tp_partition_rules_forward(self, hvd):
        mesh = make_mesh(dp=4, tp=2)
        model = self._tiny()
        imgs = jnp.zeros((4, 32, 32, 3))
        v = model.init(jax.random.PRNGKey(0), imgs)
        sharded = shard_params(v["params"], mesh, vit_partition_rules())
        qkv = sharded["layers_0"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == P(None, "tp")
        out = jax.jit(lambda p, x: model.apply({"params": p}, x))(
            sharded, imgs)
        assert np.isfinite(np.asarray(out)).all()


class TestMoEGPT:
    def _cfg(self, **kw):
        kw.setdefault("vocab_size", 64)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("head_dim", 8)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("num_experts", 4)
        kw.setdefault("dtype", jnp.float32)
        kw.setdefault("attention_impl", "reference")
        return MoEGPTConfig(**kw)

    def test_single_device_forward(self):
        model = MoEGPT(self._cfg())
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, (2, 16)), jnp.int32)
        v = model.init(jax.random.PRNGKey(0), toks)
        out = model.apply(v, toks)
        assert out.shape == (2, 16, 64)
        assert np.isfinite(np.asarray(out)).all()

    def test_aux_loss_sowed(self):
        model = MoEGPT(self._cfg())
        toks = jnp.zeros((2, 8), jnp.int32)
        v = model.init(jax.random.PRNGKey(0), toks)
        _, mut = model.apply(v, toks, mutable=["intermediates"])
        aux = moe_aux_loss(mut["intermediates"])
        # balanced-routing lower bound is 1.0 (Switch eq. 4)
        assert float(aux) >= 2.0 * 0.99  # 2 layers x >= ~1.0 each

    def test_ep_mesh_train_step_learns(self, hvd):
        """dp=2 x ep=4: experts sharded over ep, tokens all_to_all'd."""
        mesh = make_mesh(dp=2, ep=4)
        cfg = self._cfg(mesh=mesh)
        model = MoEGPT(cfg)
        r = np.random.RandomState(0)
        toks = jnp.asarray(r.randint(0, 64, (4, 16)), jnp.int32)
        tgts = jnp.roll(toks, -1, axis=1)
        v = model.init(jax.random.PRNGKey(0), toks)
        rules = moe_partition_rules()
        params = shard_params(v["params"], mesh, rules)
        up = params["layers_0"]["moe"]["up_kernel"]
        assert up.sharding.spec == P("ep")
        from horovod_tpu.training import make_gspmd_train_step
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        step = make_gspmd_train_step(
            model.apply, tx, mesh, rules,
            batch_spec=P("dp", None),
            aux_loss_fn=moe_aux_loss)
        params, opt, l1 = step(params, opt, toks, tgts)
        for _ in range(3):
            params, opt, l2 = step(params, opt, toks, tgts)
        assert np.isfinite(float(l2))
        assert float(l2) < float(l1)

    def test_ep_matches_local_when_capacity_ample(self, hvd):
        """With generous capacity and identical per-shard routing inputs,
        the distributed dispatch must agree with the all-local oracle on
        token outputs that were not dropped by either."""
        mesh = make_mesh(dp=2, ep=4)
        # capacity_factor == num_experts => capacity == all local tokens,
        # so neither path can drop and outputs must agree exactly
        cfg_d = self._cfg(mesh=mesh, num_layers=1, capacity_factor=4.0)
        cfg_l = self._cfg(num_layers=1, capacity_factor=4.0)
        model_d, model_l = MoEGPT(cfg_d), MoEGPT(cfg_l)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (4, 8)), jnp.int32)
        v = model_l.init(jax.random.PRNGKey(0), toks)
        out_l = model_l.apply(v, toks)
        out_d = model_d.apply(v, toks)
        np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_l),
                                   rtol=2e-3, atol=2e-3)


class TestSpaceToDepthStem:
    def test_stem_equivalent_to_conv7(self):
        """stem='space_to_depth' computes exactly the conv7 stem's map
        when its kernel is the stem_kernel_to_s2d rearrangement."""
        import jax
        from horovod_tpu.models.resnet import (ResNet50, space_to_depth,
                                               stem_kernel_to_s2d)
        rng = np.random.RandomState(0)
        imgs = jnp.asarray(rng.rand(2, 64, 64, 3), jnp.float32)
        m7 = ResNet50(num_classes=10, dtype=jnp.float32)
        ms = ResNet50(num_classes=10, dtype=jnp.float32,
                      stem="space_to_depth")
        v7 = m7.init(jax.random.PRNGKey(0), imgs, train=False)
        vs = jax.tree.map(lambda x: x, v7)
        k7 = v7["params"]["conv_init"]["kernel"]
        vs["params"] = {**vs["params"],
                        "conv_init": {"kernel": stem_kernel_to_s2d(k7)}}
        o7 = np.asarray(m7.apply(v7, imgs, train=False))
        os_ = np.asarray(ms.apply(vs, imgs, train=False))
        np.testing.assert_allclose(os_, o7, atol=1e-4)

    def test_space_to_depth_layout(self):
        from horovod_tpu.models.resnet import space_to_depth
        x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3).astype(jnp.float32)
        y = space_to_depth(x)
        assert y.shape == (2, 2, 2, 12)
        # channel order (dh, dw, c): y[b,i,j, dh*6+dw*3+c] = x[b,2i+dh,2j+dw,c]
        np.testing.assert_array_equal(
            np.asarray(y[0, 1, 0]),
            np.asarray(x[0, 2:4, 0:2].reshape(-1)))


class TestTopKRouting:
    def test_top2_ample_capacity_weighted_sum(self):
        """With ample capacity, top-2 output = normalized-gate-weighted
        sum of the two chosen experts' outputs."""
        from horovod_tpu.parallel.ep import topk_route
        rng = np.random.RandomState(0)
        T, E, C = 8, 4, 16
        logits = jnp.asarray(rng.randn(T, E), jnp.float32)
        dispatch, combine = topk_route(logits, E, C, k=2)
        probs = np.asarray(jax.nn.softmax(logits, -1))
        top2 = np.argsort(-probs, axis=-1)[:, :2]
        d = np.asarray(dispatch)
        c = np.asarray(combine)
        for t in range(T):
            chosen = np.where(d[t].sum(-1) > 0)[0]
            assert set(chosen) == set(top2[t])
            g = probs[t, top2[t]]
            g = g / g.sum()
            np.testing.assert_allclose(
                sorted(c[t].sum(-1)[top2[t]]), sorted(g), rtol=1e-5)

    def test_top2_capacity_drops_second_choice_first(self):
        """Under pressure, 1st choices keep their slots (GShard order)."""
        from horovod_tpu.parallel.ep import topk_route
        # all tokens prefer expert 0 then expert 1
        logits = jnp.asarray(np.tile([[2.0, 1.0, -5, -5]], (6, 1)),
                             jnp.float32)
        dispatch, _ = topk_route(logits, 4, capacity=6, k=2)
        d = np.asarray(dispatch)
        # expert 0 holds exactly its capacity of first choices
        assert d[:, 0].sum() == 6
        assert d[:, 1].sum() == 6  # second choices fill expert 1
        # a smaller capacity drops second choices, not first
        dispatch2, _ = topk_route(logits, 4, capacity=3, k=2)
        d2 = np.asarray(dispatch2)
        assert d2[:3, 0].sum() == 3 and d2[3:, 0].sum() == 0
        assert d2[:3, 1].sum() == 3

    def test_top1_backcompat(self):
        from horovod_tpu.parallel.ep import top1_route, topk_route
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(16, 4), jnp.float32)
        d1, c1 = top1_route(logits, 4, 4)
        dk, ck = topk_route(logits, 4, 4, k=1, normalize=False)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(dk))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(ck))

    def test_top2_moe_gpt_trains_on_ep_mesh(self, hvd):
        import optax
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.models.moe import (MoEGPT, MoEGPTConfig,
                                            moe_aux_loss,
                                            moe_partition_rules)
        from horovod_tpu.parallel.mesh_utils import make_mesh
        from horovod_tpu.parallel.tp import shard_params
        from horovod_tpu.training import make_gspmd_train_step
        mesh = make_mesh(dp=2, ep=4)
        cfg = MoEGPTConfig(vocab_size=64, num_layers=1, num_heads=2,
                           head_dim=8, max_seq_len=32, num_experts=4,
                           router_top_k=2, mesh=mesh, dtype=jnp.float32,
                           attention_impl="reference")
        model = MoEGPT(cfg)
        toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 16)),
                           jnp.int32)
        v = model.init(jax.random.PRNGKey(0), toks)
        params = shard_params(v["params"], mesh, moe_partition_rules())
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        step = make_gspmd_train_step(model.apply, tx, mesh,
                                     moe_partition_rules(),
                                     batch_spec=P("dp", None),
                                     aux_loss_fn=moe_aux_loss)
        losses = []
        p, o = params, opt
        tg = jnp.asarray(np.roll(np.asarray(toks), -1, 1))
        for _ in range(4):
            p, o, loss = step(p, o, toks, tg)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestVGGAndInception:
    """The rest of the reference's headline scaling-benchmark trio
    (docs/benchmarks.rst:8-13: Inception V3 / ResNet-101 / VGG-16)."""

    def test_vgg16_forward_and_train_step(self, hvd):
        import optax
        from horovod_tpu.models.vgg import VGG16
        from horovod_tpu.training import (init_replicated, make_train_step,
                                          shard_batch)
        mesh = hvd.core.basics.get_mesh()
        # avg-pool head so the size-reduced test input works; flatten is
        # the canonical 224x224 benchmark head
        model = VGG16(num_classes=10, classifier="avg", dtype=jnp.float32)
        variables = model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, 32, 32, 3), jnp.float32), train=False)
        out = model.apply(variables, jnp.ones((2, 32, 32, 3)), train=False)
        assert out.shape == (2, 10)
        assert np.isfinite(np.asarray(out)).all()
        params = init_replicated(variables["params"], mesh)
        step = make_train_step(
            lambda v, x: model.apply(v, x, train=False), optax.sgd(0.01),
            mesh)
        opt = init_replicated(step.init_opt_state(params), mesh)
        rng = np.random.RandomState(0)
        imgs = shard_batch(rng.rand(8, 32, 32, 3).astype(np.float32), mesh)
        lbls = shard_batch(rng.randint(0, 10, (8,)).astype(np.int32), mesh)
        _, _, _, loss = step(params, opt, {}, imgs, lbls)
        assert np.isfinite(float(loss))

    def test_vgg16_flatten_head_param_shapes(self):
        # classic head: first FC is 7*7*512 x 4096 at 224 input
        from horovod_tpu.models.vgg import VGG16
        model = VGG16(num_classes=1000, dtype=jnp.float32)
        variables = jax.eval_shape(
            lambda: model.init({"params": jax.random.PRNGKey(0)},
                               jnp.zeros((1, 224, 224, 3), jnp.float32),
                               train=False))
        dense0 = variables["params"]["Dense_0"]["kernel"]
        assert dense0.shape == (7 * 7 * 512, 4096), dense0.shape

    def test_inception_v3_forward(self):
        from horovod_tpu.models.inception import InceptionV3
        model = InceptionV3(num_classes=13, dtype=jnp.float32)
        variables = model.init({"params": jax.random.PRNGKey(0)},
                               jnp.zeros((1, 96, 96, 3), jnp.float32),
                               train=False)
        out = model.apply(variables, jnp.ones((2, 96, 96, 3)), train=False)
        assert out.shape == (2, 13)
        assert np.isfinite(np.asarray(out)).all()

    def test_inception_v3_grid_sizes(self):
        # 299 input must reach the canonical 8x8 grid before pooling
        # (three stem reductions + two grid reductions); check via shape
        # inference only — no FLOPs
        from horovod_tpu.models.inception import InceptionV3
        model = InceptionV3(num_classes=5, dtype=jnp.float32)
        var_shapes = jax.eval_shape(
            lambda: model.init({"params": jax.random.PRNGKey(0)},
                               jnp.zeros((1, 299, 299, 3), jnp.float32),
                               train=False))
        # final 1x1 projection in the last InceptionE sees the 2048-ch mix
        last_e = var_shapes["params"]["InceptionE_1"]
        assert last_e["ConvBN_0"]["Conv_0"]["kernel"].shape[-2] == 2048


def test_bench_model_registries_in_sync():
    """bench.py keeps a literal mirror of bench_zoo.BENCH_MODELS (so its
    parent process never imports jax); this pins the two together."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench_main", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench_main = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_main)
    from horovod_tpu.models.bench_zoo import BENCH_MODELS
    assert tuple(bench_main._BENCH_MODELS) == tuple(BENCH_MODELS)
