"""Paged KV, radix prefix sharing, speculative decoding (tier-1, CPU).

The ISSUE 10 bars (docs/serving.md):

* the block allocator never hands out an in-use block — alloc/free/
  refcount/eviction are airtight under reuse and sharing;
* paged decode (block pool + block tables) emits EXACTLY the tokens of
  the slotted/straight-line greedy oracle, for GPT and Llama-GQA,
  across admission waves that recycle rows and blocks;
* prefix-shared prefills (full-block reuse AND a copy-on-write
  divergence mid-block) stay bit-identical, and the shared source
  block is never mutated by a non-owner;
* speculative decoding — including a drafter whose proposals get
  REJECTED and rolled back — emits the target's greedy stream
  bit-identically (same tokens, same stop positions) and wins
  < 0.7 target steps per token when the drafter agrees;
* deadline-expired and shed requests release every block reference and
  prefix refcount in the same iteration: zero leaked blocks after an
  overload burst;
* a chaos ``serve.kv`` corrupt flips a bit in a real pool BLOCK and
  the per-block crc catches it before tokens reach a client;
* the new config knobs parse strictly; the fleet flushes a recovered
  replica's prefix cache before re-admission (stale-weight KV can
  never serve a new version).
"""
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.chaos import inject
from horovod_tpu.chaos.plan import ChaosPlan
from horovod_tpu.core.config import Config
from horovod_tpu.models.gpt import GPT, GPTConfig
from horovod_tpu.models.llama import Llama, LlamaConfig
from horovod_tpu.serve import (AdmissionQueue, BlockPool, ContinuousBatcher,
                               PagedKVCache, RadixPrefixCache, Rejected,
                               ShardedExecutor)

_KW = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
           max_seq_len=48, dtype=jnp.float32, attention_impl="reference")
_BS, _POOL = 4, 32


@pytest.fixture(autouse=True)
def _disarm():
    inject.uninstall()
    yield
    inject.uninstall()


@pytest.fixture(scope="module")
def gpt():
    """Tiny GPT in three flavors over ONE param set: training-mode
    oracle, slotted decode, paged decode."""
    train = GPT(GPTConfig(**_KW))
    paged = GPT(GPTConfig(decode=True, **_KW, kv_block_size=_BS,
                          kv_pool_blocks=_POOL))
    slotted = GPT(GPTConfig(decode=True, **_KW))
    params = train.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    # a DIFFERENT drafter (disagrees with the target almost always —
    # the rejection/rollback path) and a PERFECT drafter (same params)
    draft_params = train.init(jax.random.PRNGKey(9),
                              jnp.zeros((2, 8), jnp.int32))["params"]

    @jax.jit
    def onext(p, padded, last):
        return jnp.argmax(jnp.take(
            train.apply({"params": p}, padded)[0], last, axis=0))

    def oracle(prompt, max_new, eos_id=None):
        seq, out = list(prompt), []
        for _ in range(max_new):
            padded = np.zeros((1, _KW["max_seq_len"]), np.int32)
            padded[0, :len(seq)] = seq
            nxt = int(onext(params, jnp.asarray(padded),
                            jnp.asarray(len(seq) - 1)))
            out.append(nxt)
            seq.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return out

    return SimpleNamespace(paged=paged, slotted=slotted, params=params,
                           draft_params=draft_params, oracle=oracle)


def _stack(gpt, *, max_batch=4, max_queue=32, buckets=(16,),
           deadline_ms=30000.0, prefix=True, kv_crc=False,
           draft=None, spec_k=3, eos_id=None, warmup=True):
    ex = ShardedExecutor(gpt.paged, gpt.params, max_batch=max_batch,
                         max_len=_KW["max_seq_len"])
    q = AdmissionQueue(max_queue=max_queue,
                       default_deadline_ms=deadline_ms)
    b = ContinuousBatcher(ex, q, buckets=buckets, prefix_cache=prefix,
                          kv_crc=kv_crc, draft_executor=draft,
                          spec_k=spec_k, eos_id=eos_id)
    if warmup:
        b.warmup()
    return ex, q, b


def _draft_ex(gpt, params, max_batch=4):
    return ShardedExecutor(gpt.slotted, params, max_batch=max_batch,
                           max_len=_KW["max_seq_len"], role="draft")


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------

class TestBlockPool:
    def test_alloc_free_refcount_never_hand_out_in_use(self):
        pool = BlockPool(4, 8)
        blocks = [pool.alloc() for _ in range(4)]
        assert sorted(blocks) == [0, 1, 2, 3]
        assert pool.alloc() is None            # exhausted
        assert pool.in_use() == 4 and pool.occupancy() == 1.0
        # a shared block survives its first owner's release
        pool.incref(blocks[0])
        assert not pool.decref(blocks[0])      # still referenced
        assert pool.alloc() is None            # NOT handed out again
        assert pool.decref(blocks[0])          # last ref -> freed
        got = pool.alloc()
        assert got == blocks[0]                # LIFO reuse
        pool2 = BlockPool(2, 4)
        a = pool2.alloc()
        pool2.decref(a)
        with pytest.raises(ValueError):        # double free
            pool2.decref(a)
        with pytest.raises(ValueError):        # sharing a dead block
            pool2.incref(a)

    def test_every_alloc_is_refcount_zero(self):
        """Randomized churn: the free list never yields a block whose
        refcount is nonzero (the alloc() assertion is the real gate;
        this drives it through interleaved share/release)."""
        rng = np.random.RandomState(3)
        pool = BlockPool(8, 4)
        live = []
        for _ in range(500):
            op = rng.randint(3)
            if op == 0:
                blk = pool.alloc()
                if blk is not None:
                    live.append(blk)
            elif op == 1 and live:
                blk = live[rng.randint(len(live))]
                pool.incref(blk)
                live.append(blk)               # one extra release due
            elif op == 2 and live:
                blk = live.pop(rng.randint(len(live)))
                pool.decref(blk)
        assert pool.in_use() + pool.free_count() == 8

    def test_block_crc_ledger_stream_reset_clone(self):
        pool = BlockPool(4, 8)
        a, b = pool.alloc(), pool.alloc()
        pool.crc_stream(a, [b"ab", b"12"], 2)
        pool.crc_stream(a, [b"cd", b"34"], 4)
        assert pool.crc_filled(a) == 4
        assert pool.crc_check(a, [b"abcd", b"1234"])
        assert not pool.crc_check(a, [b"abcX", b"1234"])
        pool.crc_clone(a, b)                   # CoW bookkeeping
        assert pool.crc_check(b, [b"abcd", b"1234"])
        pool.crc_reset(a, [b"zz", b"99"], 2)   # rollback recompute
        assert pool.crc_check(a, [b"zz", b"99"])
        pool.decref(a)
        assert pool.crc_filled(a) == 0         # ledger dies with block

    def test_paged_cache_reservation_gate(self):
        pool = BlockPool(8, 4)
        kv = PagedKVCache(2, 4, pool)
        assert kv.blocks_needed(1) == 1 and kv.blocks_needed(9) == 3
        assert kv.can_admit(5)
        r0 = kv.alloc_row(5)                   # reserve 5 of 8
        assert kv.available_blocks() == 3
        assert not kv.can_admit(4)             # would starve row 0
        assert kv.can_admit(3)
        kv.ensure(r0, 9)                       # 3 blocks materialize
        assert pool.in_use() == 3 and kv.reserved[r0] == 2
        kv.free_row(r0)
        assert pool.in_use() == 0 and kv.reserved_total() == 0
        with pytest.raises(ValueError):
            kv.free_row(r0)

    def test_reserved_append_never_starves(self):
        """The admission invariant: growth the gate admitted always
        finds a block, even when the free list momentarily drains."""
        pool = BlockPool(2, 4)
        kv = PagedKVCache(2, 2, pool)
        r0 = kv.alloc_row(2)
        assert not kv.can_admit(1)             # both blocks spoken for
        assert [pool.refcount[b] for b in kv.ensure(r0, 8)] == [1, 1]
        with pytest.raises(RuntimeError):      # UNreserved growth trips
            kv.append_block(r0)


# ---------------------------------------------------------------------------
# paged decode correctness
# ---------------------------------------------------------------------------

class TestPagedDecode:
    def test_row_and_block_reuse_matches_oracle(self, gpt):
        """8 requests over 4 rows: the second wave recycles rows AND
        pool blocks still holding the first wave's bytes."""
        ex, q, b = _stack(gpt, prefix=False)
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 64, rng.randint(2, 9)))
                   for _ in range(8)]
        handles = [q.submit(p, max_new_tokens=6) for p in prompts]
        b.run()
        assert b.kv.generation.sum() >= 5      # rows actually recycled
        assert b.kv.pool.frees > 0             # blocks returned + reused
        for p, h in zip(prompts, handles):
            assert h.status == "ok"
            assert h.tokens == gpt.oracle(p, 6)
        assert b.kv.pool.in_use() == 0         # nothing leaked

    def test_llama_gqa_paged_matches_oracle(self):
        kw = dict(vocab_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, head_dim=8, max_seq_len=32,
                  dtype=jnp.float32, attention_impl="reference")
        train = Llama(LlamaConfig(**kw))
        dec = Llama(LlamaConfig(decode=True, **kw, kv_block_size=4,
                                kv_pool_blocks=24))
        params = train.init(jax.random.PRNGKey(0),
                            jnp.zeros((2, 8), jnp.int32))["params"]
        ex = ShardedExecutor(dec, params, max_batch=2, max_len=32)
        q = AdmissionQueue(max_queue=8)
        b = ContinuousBatcher(ex, q, buckets=(8,), prefix_cache=True)
        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, 64, 5)) for _ in range(3)]
        handles = [q.submit(p, max_new_tokens=4) for p in prompts]
        b.run()

        @jax.jit
        def onext(p, padded, last):
            return jnp.argmax(jnp.take(
                train.apply({"params": p}, padded)[0], last, axis=0))

        for p, h in zip(prompts, handles):
            seq, want = list(p), []
            for _ in range(4):
                padded = np.zeros((1, 32), np.int32)
                padded[0, :len(seq)] = seq
                nxt = int(onext(params, jnp.asarray(padded),
                                jnp.asarray(len(seq) - 1)))
                want.append(nxt)
                seq.append(nxt)
            assert h.status == "ok" and h.tokens == want

    def test_jit_cache_flat_across_paged_churn(self, gpt):
        """Paged + speculative: post-warmup churn (mixed lengths
        joining mid-flight, rows and blocks recycling, CoW copies)
        adds zero compiled programs."""
        draft = _draft_ex(gpt, gpt.params, max_batch=3)
        ex, q, b = _stack(gpt, max_batch=3, draft=draft, spec_k=2)
        baseline = ex.jit_cache_size()
        dbase = draft.jit_cache_size()
        rng = np.random.RandomState(4)
        handles = [q.submit(list(rng.randint(0, 64, n)), max_new_tokens=m)
                   for n, m in ((2, 9), (7, 3), (5, 5))]
        for i in range(40):
            alive = b.step()
            if i in (2, 5, 9):
                handles.append(q.submit(
                    list(rng.randint(0, 64, rng.randint(2, 16))),
                    max_new_tokens=int(rng.randint(1, 8))))
            if not alive and q.depth() == 0:
                break
        b.run()
        assert all(h.status == "ok" for h in handles)
        assert ex.jit_cache_size() == baseline
        assert draft.jit_cache_size() == dbase


# ---------------------------------------------------------------------------
# radix prefix cache
# ---------------------------------------------------------------------------

class TestPrefixCacheUnit:
    def _cache(self, blocks=16, bs=4):
        pool = BlockPool(blocks, bs)
        return pool, RadixPrefixCache(pool)

    def _publish(self, pool, cache, prompt):
        """Simulate a prefill owner: allocate that prompt's full
        blocks, insert, then retire the owner (tree keeps its refs)."""
        n_full = len(prompt) // pool.block_size
        blks = [pool.alloc() for _ in range(n_full)]
        cache.insert(prompt, blks)
        for b in blks:
            pool.decref(b)
        return blks

    def test_match_refcounts_and_release(self):
        pool, cache = self._cache()
        blks = self._publish(pool, cache, list(range(12)))
        assert len(cache) == 3
        full, partial, m = cache.match(list(range(12)) + [50])
        assert m == 12 and partial is None and full == blks
        assert all(pool.refcount[b] == 2 for b in full)  # tree + caller
        cache.release(full)
        assert all(pool.refcount[b] == 1 for b in full)
        # a mid-block divergence pins the partial source temporarily
        full, partial, m = cache.match(list(range(10)) + [50, 51])
        assert len(full) == 2 and partial == (blks[2], 2) and m == 10
        cache.release(full + [partial[0]])

    def test_match_caps_at_prompt_minus_one(self):
        """At least one prompt token must be prefilled (the request
        needs a last-logit to sample from)."""
        pool, cache = self._cache()
        blks = self._publish(pool, cache, list(range(8)))
        # the prompt IS the cached run: a full match would leave zero
        # tokens to prefill, so the 2nd block may only match partially
        full, partial, m = cache.match(list(range(8)))
        assert m == 7 and full == [blks[0]]
        assert partial == (blks[1], 3)
        cache.release(full + [partial[0]])

    def test_lru_eviction_leaves_first_and_pinned_paths_survive(self):
        pool, cache = self._cache(blocks=8)
        a = self._publish(pool, cache, [1, 2, 3, 4, 5, 6, 7, 8])
        b = self._publish(pool, cache, [9, 10, 11, 12])
        # touch the [1..8] path so [9..12] is LRU
        full, partial, _ = cache.match([1, 2, 3, 4, 5, 6, 7, 8, 0])
        cache.release(full + ([partial[0]] if partial else []))
        assert cache.evictable_blocks() == 3
        assert cache.evict(1) == 1
        assert pool.refcount[b[0]] == 0        # the LRU leaf died
        # pin [1..8]'s leaf: its whole path becomes unevictable
        pool.incref(a[1])
        assert cache.evictable_blocks() == 0
        assert cache.evict(4) == 0
        pool.decref(a[1])
        assert cache.evict(4) == 2             # cascades up the path
        assert len(cache) == 0

    def test_flush_returns_tree_refs_only(self):
        pool, cache = self._cache()
        blks = self._publish(pool, cache, list(range(8)))
        pool.incref(blks[0])                   # a live sequence shares
        assert cache.flush() == 2
        assert len(cache) == 0
        assert pool.refcount[blks[0]] == 1     # survives under owner
        assert pool.refcount[blks[1]] == 0


class TestPrefixSharing:
    def test_shared_system_prompt_bit_identical_and_counted(self, gpt):
        """Wave 1 publishes the system prompt's blocks; wave 2 reuses
        them — same tokens as the oracle, tokens_saved > 0, and the
        pool holds ONE copy of the shared run."""
        ex, q, b = _stack(gpt, max_batch=4, buckets=(16,))
        rng = np.random.RandomState(5)
        system = list(rng.randint(0, 64, 8))   # 2 full blocks
        h0 = q.submit(system + [1, 2], max_new_tokens=5)
        b.run()                                # publish
        assert b.prefix.misses >= 1
        prompts = [system + list(rng.randint(0, 64, k)) for k in (2, 3)]
        handles = [q.submit(p, max_new_tokens=5) for p in prompts]
        b.run()
        assert h0.tokens == gpt.oracle(system + [1, 2], 5)
        for p, h in zip(prompts, handles):
            assert h.status == "ok" and h.tokens == gpt.oracle(p, 5)
        assert b.prefix.hits == 2
        assert b.prefix.tokens_saved == 16     # 2 blocks x 2 requests
        # the tree holds one copy of the shared run, still resident
        assert b.kv.pool.in_use() == len(b.prefix)

    def test_cow_divergence_mid_block_never_mutates_source(self, gpt):
        """A prompt diverging INSIDE a cached block copies it (CoW) and
        overwrites only its own copy: the original owner's prompt still
        matches byte-identically afterwards."""
        ex, q, b = _stack(gpt, max_batch=4, buckets=(16,))
        base = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]   # 3 full blocks
        h0 = q.submit(base, max_new_tokens=4)
        b.run()
        saved0 = b.prefix.tokens_saved
        # diverges at position 10 — inside the 3rd block
        fork = base[:10] + [60, 61]
        h1 = q.submit(fork, max_new_tokens=4)
        b.run()
        assert b.prefix.tokens_saved - saved0 == 10   # 8 full + 2 CoW
        # the source block was copied, not written: re-serving the
        # ORIGINAL prompt from cache still matches the oracle
        h2 = q.submit(base + [7], max_new_tokens=4)
        b.run()
        assert h0.tokens == gpt.oracle(base, 4)
        assert h1.tokens == gpt.oracle(fork, 4)
        assert h2.tokens == gpt.oracle(base + [7], 4)

    def test_weight_swap_flushes_prefix_cache(self, gpt):
        ex, q, b = _stack(gpt, max_batch=2, buckets=(16,))
        q.submit([1, 2, 3, 4, 5, 6, 7, 8, 9], max_new_tokens=2)
        b.run()
        assert len(b.prefix) > 0
        ex.swap_params(gpt.params, version=2)  # same values, new version
        q.submit([1, 2, 3], max_new_tokens=1)
        b.run()
        # flushed BEFORE the new admission could match, then the new
        # prompt re-published under v2
        assert b._prefix_version == 2
        assert b.prefix.hits == 0

    def test_router_requested_flush_runs_before_admission(self, gpt):
        ex, q, b = _stack(gpt, max_batch=2, buckets=(16,))
        q.submit(list(range(1, 10)), max_new_tokens=1)
        b.run()
        assert len(b.prefix) > 0
        b.request_prefix_flush()
        q.submit(list(range(1, 10)), max_new_tokens=1)
        b.run()
        assert b.prefix.hits == 0              # the re-walk was a miss


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculative:
    def test_perfect_drafter_bit_identical_and_step_win(self, gpt):
        """Drafter == target: every proposal accepted; emitted stream
        identical to plain greedy; < 0.7 target steps per token (the
        machine-independent win the bench gate asserts)."""
        draft = _draft_ex(gpt, gpt.params)
        ex, q, b = _stack(gpt, draft=draft, spec_k=3, prefix=False)
        rng = np.random.RandomState(6)
        prompts = [list(rng.randint(0, 64, rng.randint(2, 9)))
                   for _ in range(6)]
        handles = [q.submit(p, max_new_tokens=8) for p in prompts]
        b.run()
        for p, h in zip(prompts, handles):
            assert h.status == "ok" and h.tokens == gpt.oracle(p, 8)
        assert b.gen_tokens > 0
        assert b.gen_steps / b.gen_tokens < 0.7

    def test_rejecting_drafter_rolls_back_bit_identically(self, gpt):
        """A drafter with DIFFERENT params disagrees with the target
        almost everywhere: nearly every draft is rejected, the write-
        ahead is rolled back, and the emitted stream is still exactly
        the target's greedy stream."""
        draft = _draft_ex(gpt, gpt.draft_params)
        ex, q, b = _stack(gpt, draft=draft, spec_k=3, kv_crc=True)
        rng = np.random.RandomState(7)
        prompts = [list(rng.randint(0, 64, rng.randint(2, 9)))
                   for _ in range(6)]
        handles = [q.submit(p, max_new_tokens=7) for p in prompts]
        b.run()
        for p, h in zip(prompts, handles):
            assert h.status == "ok" and h.tokens == gpt.oracle(p, 7)
        # rollback actually happened: more target steps than a
        # full-accept run would need (7 tokens needs >= 2 verify steps
        # even at full accept; rejection pushes it near 1 step/token)
        assert b.gen_steps / b.gen_tokens > 0.5

    def test_eos_stop_positions_identical(self, gpt):
        """EOS inside an ACCEPTED draft run must stop the stream at
        exactly the position plain greedy decode stops."""
        rng = np.random.RandomState(8)
        prompts = [list(rng.randint(0, 64, 5)) for _ in range(4)]
        # pick an eos that actually occurs mid-stream for some prompt
        eos = gpt.oracle(prompts[0], 8)[2]
        want = [gpt.oracle(p, 8, eos_id=eos) for p in prompts]
        draft = _draft_ex(gpt, gpt.params)
        ex, q, b = _stack(gpt, draft=draft, spec_k=3, prefix=False,
                          eos_id=eos)
        handles = [q.submit(p, max_new_tokens=8) for p in prompts]
        b.run()
        for w, h in zip(want, handles):
            assert h.status == "ok" and h.tokens == w

    def test_spec_with_prefix_and_llama_gqa_target(self):
        """The ISSUE pairing: GPT drafter proposing, Llama-GQA target
        verifying — paged + prefix-shared + speculative all on, output
        bit-identical to the Llama-only greedy oracle."""
        kw = dict(vocab_size=64, num_layers=2, num_heads=4,
                  num_kv_heads=2, head_dim=8, max_seq_len=48,
                  dtype=jnp.float32, attention_impl="reference")
        train = Llama(LlamaConfig(**kw))
        dec = Llama(LlamaConfig(decode=True, **kw, kv_block_size=4,
                                kv_pool_blocks=32))
        params = train.init(jax.random.PRNGKey(0),
                            jnp.zeros((2, 8), jnp.int32))["params"]
        gkw = dict(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                   max_seq_len=48, dtype=jnp.float32,
                   attention_impl="reference")
        gdraft = GPT(GPTConfig(decode=True, **gkw))
        gparams = GPT(GPTConfig(**gkw)).init(
            jax.random.PRNGKey(3), jnp.zeros((2, 8), jnp.int32))["params"]
        ex = ShardedExecutor(dec, params, max_batch=2, max_len=48)
        draft = ShardedExecutor(gdraft, gparams, max_batch=2,
                                max_len=48, role="draft")
        q = AdmissionQueue(max_queue=8)
        b = ContinuousBatcher(ex, q, buckets=(16,), prefix_cache=True,
                              draft_executor=draft, spec_k=2,
                              kv_crc=True)
        rng = np.random.RandomState(11)
        system = list(rng.randint(0, 64, 8))
        prompts = [system + list(rng.randint(0, 64, 3))
                   for _ in range(4)]
        handles = [q.submit(p, max_new_tokens=5) for p in prompts]
        b.run()

        @jax.jit
        def onext(p, padded, last):
            return jnp.argmax(jnp.take(
                train.apply({"params": p}, padded)[0], last, axis=0))

        for p, h in zip(prompts, handles):
            seq, want = list(p), []
            for _ in range(5):
                padded = np.zeros((1, 48), np.int32)
                padded[0, :len(seq)] = seq
                nxt = int(onext(params, jnp.asarray(padded),
                                jnp.asarray(len(seq) - 1)))
                want.append(nxt)
                seq.append(nxt)
            assert h.status == "ok" and h.tokens == want
        assert b.prefix.hits >= 1              # sharing + spec compose


# ---------------------------------------------------------------------------
# block release discipline (expiry / shed / overload)
# ---------------------------------------------------------------------------

class TestBlockRelease:
    def test_zero_leaked_blocks_after_overload_burst(self, gpt):
        """The PR 2 slot-free-on-expiry bar re-targeted at blocks: a
        burst that triggers shed + deadline expiry mid-decode leaves
        ZERO blocks allocated once drained (prefix cache off so any
        resident block would be a leak)."""
        ex, q, b = _stack(gpt, max_batch=2, max_queue=4, prefix=False,
                          deadline_ms=5.0)
        rng = np.random.RandomState(12)
        handles, shed = [], 0
        for _ in range(12):
            try:
                handles.append(q.submit(list(rng.randint(0, 64, 6)),
                                        max_new_tokens=40))
            except Rejected:
                shed += 1
        b.run()
        assert shed > 0
        assert any(h.status == "expired" for h in handles)
        assert b.kv.live() == 0
        assert b.kv.pool.in_use() == 0         # zero leaked blocks
        assert b.kv.reserved_total() == 0
        # capacity actually restored: a fresh request completes
        h2 = q.submit(list(range(4)), max_new_tokens=2,
                      deadline_ms=30000.0)
        b.run()
        assert h2.status == "ok" and len(h2.tokens) == 2

    def test_expiry_decrements_prefix_refcounts_same_iteration(self, gpt):
        """An expired sequence holding SHARED prefix blocks returns its
        references; the tree's own refcount keeps the run cached."""
        ex, q, b = _stack(gpt, max_batch=2, buckets=(16,))
        system = list(range(1, 9))             # 2 full blocks
        q.submit(system + [9], max_new_tokens=2)
        b.run()                                # publish
        resident = b.kv.pool.in_use()
        h = q.submit(system + [10], max_new_tokens=40, deadline_ms=5.0)
        b.run()
        assert h.status == "expired"
        assert b.kv.live() == 0
        # only the tree's references remain — the expired sequence's
        # shares and private blocks all came back
        assert b.kv.pool.in_use() == resident == len(b.prefix)

    def test_blocked_reprefill_is_not_queue_jumped(self, gpt):
        """A corrupted-and-reset request waiting in the reprefill lane
        is AHEAD of the queue: while its block budget doesn't fit, no
        queued request may admit past it and eat the blocks it waits
        for (it would starve to its deadline parked there)."""
        ex, q, b = _stack(gpt, max_batch=4, buckets=(16,), prefix=False)
        hogs = [q.submit(list(np.random.RandomState(s).randint(0, 64, 12)),
                         max_new_tokens=30) for s in (30, 31)]
        for _ in range(2):
            b.step()
        # park a big request in the reprefill lane (what a detected KV
        # corruption does), too big for the blocks currently free
        big = q.submit(list(np.random.RandomState(32).randint(0, 64, 12)),
                       max_new_tokens=30)
        b._reprefill.append(q.pop(1)[0])
        small = q.submit([1, 2, 3], max_new_tokens=1)
        b.step()
        assert b._reprefill                     # still blocked...
        assert q.depth() == 1                   # ...and small NOT past it
        b.run()                                 # hogs retire -> both go
        assert big.status == "ok" and small.status == "ok"
        assert all(h.status == "ok" for h in hogs)
        assert b.kv.pool.in_use() == 0

    def test_failed_admission_releases_matched_plan(self, gpt):
        """A prefix match whose admission falls through (no free
        blocks) must drop its pinned references — the queue-head
        request admits later instead of deadlocking the pool."""
        ex, q, b = _stack(gpt, max_batch=4, buckets=(16,))
        system = list(range(1, 13))            # 3 full blocks
        q.submit(system, max_new_tokens=1)
        b.run()
        # occupy nearly the whole pool with held rows (don't drain)
        hogs = [q.submit(list(np.random.RandomState(s).randint(0, 64, 12)),
                         max_new_tokens=30) for s in (20, 21, 22)]
        for _ in range(3):
            b.step()
        refc0 = int(b.kv.pool.refcount.sum())
        h = q.submit(system + [5], max_new_tokens=30)
        b.step()                               # match pinned + released
        assert int(b.kv.pool.refcount.sum()) >= refc0  # hog growth ok
        b.run()                                # hogs finish, h admits
        assert h.status == "ok"
        assert all(x.status == "ok" for x in hogs)
        assert b.kv.live() == 0


# ---------------------------------------------------------------------------
# chaos: serve.kv corrupt on a pool BLOCK
# ---------------------------------------------------------------------------

class TestPagedKVChaos:
    def test_block_corrupt_caught_by_per_block_crc(self, gpt):
        """The serve.kv fault flips a real bit inside a pool block; the
        per-block crc catches it at verify-on-read, the sequence
        re-prefills, and the client still gets oracle tokens."""
        plan = ChaosPlan.from_dict({"faults": [
            {"rank": 0, "site": "serve.kv", "kind": "corrupt",
             "at": 3}]})
        inject.install(plan, rank=0)
        ex, q, b = _stack(gpt, max_batch=2, kv_crc=True, prefix=True)
        rng = np.random.RandomState(13)
        prompts = [list(rng.randint(0, 64, 6)) for _ in range(2)]
        handles = [q.submit(p, max_new_tokens=8) for p in prompts]
        b.run()
        assert b.kv_corruptions_injected == 1
        assert b.kv_corruptions_detected >= 1
        assert b.kv_reprefills >= 1
        for p, h in zip(prompts, handles):
            assert h.status == "ok" and h.tokens == gpt.oracle(p, 8)

    def test_shared_prefix_block_corrupt_flushes_cache(self, gpt):
        """Corruption landing in a SHARED prefix block must not be
        re-matched by the re-prefill: detection flushes the tree."""
        plan = ChaosPlan.from_dict({"faults": [
            {"rank": 0, "site": "serve.kv", "kind": "corrupt",
             "at": 6, "slot": 0}]})
        inject.install(plan, rank=0)
        ex, q, b = _stack(gpt, max_batch=2, kv_crc=True, prefix=True,
                          buckets=(16,))
        system = list(range(1, 10))
        h0 = q.submit(system, max_new_tokens=2)
        b.run()
        h1 = q.submit(system + [3], max_new_tokens=8)
        b.run()
        assert b.kv_corruptions_injected == 1
        assert b.kv_corruptions_detected >= 1
        assert h0.status == "ok" and h1.status == "ok"
        assert h1.tokens == gpt.oracle(system + [3], 8)


# ---------------------------------------------------------------------------
# fleet re-admission: the KV side of the weight gate
# ---------------------------------------------------------------------------

class TestFleetReadmissionFlush:
    def _paged_fleet(self, gpt, subscribers=None):
        from horovod_tpu.serve import FleetRouter, Replica
        reps = [
            Replica(i,
                    ShardedExecutor(gpt.paged, gpt.params, max_batch=4,
                                    max_len=_KW["max_seq_len"],
                                    replica_id=i),
                    buckets=(16,), max_queue=32, prefix_cache=True,
                    subscriber=(subscribers or {}).get(i))
            for i in range(2)]
        router = FleetRouter(reps, interval_s=0.1, suspect_s=0.5)
        return router, reps

    def _eject_and_recover(self, router, reps, events, mid_eject=None):
        """Populate replica 0's prefix cache, freeze its heartbeat so
        the router ejects it (slow path — the batcher and its prefix
        cache SURVIVE), run ``mid_eject``, unfreeze, wait for
        re-admission."""
        system = list(range(1, 10))
        deadline = time.monotonic() + 30
        if reps[0].subscriber is not None:
            # let the initial v1 adoption (and its version-fence flush)
            # land first, or it would wipe the tree we populate below
            while any(r.batcher._prefix_version is None
                      or r.batcher._prefix_version
                      != r.executor.params_version for r in reps):
                assert time.monotonic() < deadline
                time.sleep(0.02)
        while len(reps[0].batcher.prefix) == 0:
            assert time.monotonic() < deadline
            router.submit(system + [int(time.monotonic() * 997) % 60],
                          max_new_tokens=2).wait(10)
        reps[0].batcher.heartbeat = lambda: None   # wedge heartbeats
        while not any(e["event"] == "eject" and e["replica"] == 0
                      for e in events):
            assert time.monotonic() < deadline, events
            time.sleep(0.02)
        assert len(reps[0].batcher.prefix) > 0     # survived ejection
        if mid_eject is not None:
            mid_eject()
        reps[0].batcher.heartbeat = reps[0]._heartbeat
        while not any(e["event"] == "readmit" and e["replica"] == 0
                      for e in events):
            assert time.monotonic() < deadline, events
            time.sleep(0.02)
        # the flush lands on the scheduler thread's next iteration
        while len(reps[0].batcher.prefix) > 0:
            assert time.monotonic() < deadline, \
                "recovered replica rejoined with its stale prefix cache"
            time.sleep(0.02)

    def test_readmitted_replica_prefix_cache_flushed(self, gpt):
        """A slow-but-alive replica keeps its batcher across ejection;
        re-admission must flush its prefix cache even when NO weight
        version changed while it was out (it cannot know what it
        missed — conservative gate)."""
        router, reps = self._paged_fleet(gpt)
        events = []
        router.add_listener(lambda ev: events.append(ev))
        router.start()
        try:
            self._eject_and_recover(router, reps, events)
            h = router.submit(list(range(1, 10)), max_new_tokens=2)
            assert h.wait(20) and h.status == "ok"
        finally:
            router.close()

    def test_v2_published_mid_eject_never_serves_v1_prefix(self, gpt):
        """The ISSUE regression: weights move to v2 while the replica
        is ejected; on re-admission its v1 prefix runs are flushed
        BEFORE any prompt can match them, and it serves v2."""
        from horovod_tpu.native.store import StoreServer
        from horovod_tpu.redist.stream import (WeightPublisher,
                                               WeightSubscriber)
        with StoreServer() as srv:
            pub = WeightPublisher("kvgate", kv_addr="127.0.0.1",
                                  kv_port=srv.port, resume_timeout=0.05)
            pub.publish(gpt.params)                    # v1
            subs = {i: WeightSubscriber("kvgate", kv_addr="127.0.0.1",
                                        kv_port=srv.port,
                                        template=gpt.params)
                    for i in range(2)}
            router, reps = self._paged_fleet(gpt, subscribers=subs)
            events = []
            router.add_listener(lambda ev: events.append(ev))
            router.start()
            try:
                self._eject_and_recover(
                    router, reps, events,
                    mid_eject=lambda: pub.publish(gpt.params))  # v2
                assert reps[0].executor.params_version == 2
                # same values under v2, so service stays bit-identical
                h = router.submit(list(range(1, 10)), max_new_tokens=3)
                assert h.wait(20) and h.status == "ok"
                assert h.tokens == gpt.oracle(list(range(1, 10)), 3)
            finally:
                router.close()
                pub.close()
                for s in subs.values():
                    s.close()


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------

class TestPagedConfigKnobs:
    def test_defaults(self):
        c = Config()
        c.validate()
        assert c.serve_kv_block == 0
        assert c.serve_prefix_cache is True
        assert c.serve_spec_k == 3

    def test_env_parsing(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SERVE_KV_BLOCK", "16")
        monkeypatch.setenv("HOROVOD_SERVE_PREFIX_CACHE", "0")
        monkeypatch.setenv("HOROVOD_SERVE_SPEC_K", "5")
        c = Config.from_env()
        assert c.serve_kv_block == 16
        assert c.serve_prefix_cache is False
        assert c.serve_spec_k == 5

    @pytest.mark.parametrize("name,val", [
        ("HOROVOD_SERVE_KV_BLOCK", "big"),
        ("HOROVOD_SERVE_KV_BLOCK", "-1"),
        ("HOROVOD_SERVE_KV_BLOCK", "8192"),
        ("HOROVOD_SERVE_SPEC_K", "-1"),
        ("HOROVOD_SERVE_SPEC_K", "k"),
        ("HOROVOD_SERVE_SPEC_K", "100"),
    ])
    def test_bad_env_fails_fast(self, monkeypatch, name, val):
        monkeypatch.setenv(name, val)
        with pytest.raises(ValueError):
            Config.from_env()

    def test_paged_model_kwargs_reads_env(self, monkeypatch):
        """HOROVOD_SERVE_KV_BLOCK's consumer: the helper that turns the
        env knob into model-config pool shapes."""
        from horovod_tpu.serve import paged_model_kwargs
        monkeypatch.delenv("HOROVOD_SERVE_KV_BLOCK", raising=False)
        assert paged_model_kwargs(4, 48) == {}      # slotted default
        monkeypatch.setenv("HOROVOD_SERVE_KV_BLOCK", "4")
        kw = paged_model_kwargs(4, 48)
        assert kw["kv_block_size"] == 4
        assert kw["kv_pool_blocks"] >= 12 + 4       # one max_len seq fits
        model = GPT(GPTConfig(decode=True, **_KW, **kw))
        assert model.cfg.kv_block_size == 4

    def test_model_config_validation(self):
        with pytest.raises(ValueError):        # paged is decode-only
            GPTConfig(kv_block_size=4, kv_pool_blocks=8, **_KW)
        with pytest.raises(ValueError):        # pool shape is static
            GPTConfig(decode=True, kv_block_size=4, **_KW)
        with pytest.raises(ValueError):
            LlamaConfig(decode=True, kv_block_size=4,
                        vocab_size=64, num_layers=1, num_heads=2,
                        head_dim=8, max_seq_len=32)

    def test_executor_rejects_undersized_pool(self, gpt):
        small = GPT(GPTConfig(decode=True, **_KW, kv_block_size=4,
                              kv_pool_blocks=4))
        with pytest.raises(ValueError):        # can't hold one max_len seq
            ShardedExecutor(small, gpt.params, max_batch=2, max_len=48)

    def test_draft_executor_must_be_slotted_and_matched(self, gpt):
        ex = ShardedExecutor(gpt.paged, gpt.params, max_batch=2,
                             max_len=48)
        q = AdmissionQueue(max_queue=4)
        paged_draft = ShardedExecutor(gpt.paged, gpt.params,
                                      max_batch=2, max_len=48,
                                      role="draft")
        with pytest.raises(ValueError):
            ContinuousBatcher(ex, q, buckets=(8,),
                              draft_executor=paged_draft, spec_k=2,
                              prefix_cache=False)
        mismatched = ShardedExecutor(gpt.slotted, gpt.params,
                                     max_batch=3, max_len=48,
                                     role="draft")
        with pytest.raises(ValueError):
            ContinuousBatcher(ex, q, buckets=(8,),
                              draft_executor=mismatched, spec_k=2,
                              prefix_cache=False)
