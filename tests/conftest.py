"""Test configuration: force an 8-device CPU platform so every collective
test exercises a real multi-device mesh without TPU hardware (the analog of
the reference running parallel tests under mpirun -np N,
.buildkite/gen-pipeline.sh:140).

Note: jax may already be imported by the interpreter's sitecustomize, so the
platform is overridden via jax.config (effective until the backend
initializes) rather than env vars alone.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8").strip()

# Collective-op stall bound for the binding plane (reference
# HOROVOD_GLOO_TIMEOUT_SECONDS). The product default (60 s shm / 300 s
# store) is right for real jobs, but a full-suite run oversubscribes
# this 1-core container so badly that a worker can be starved past 60 s
# INSIDE a barrier — the one observed suite flake
# (test_keras_estimator_multiprocess, docs/round5_notes.md). Children
# of every multiprocess test inherit this.
os.environ.setdefault("HOROVOD_GLOO_TIMEOUT_SECONDS", "600")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# jax-version shim (jax.shard_map moved namespaces across releases) must be
# in place before test modules that do `from jax import shard_map` are
# collected.
import horovod_tpu._compat  # noqa: E402,F401

import pytest  # noqa: E402


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd
    hvd.init()
    yield hvd
    hvd.shutdown()


# --------------------------------------------------------------------------
# runtime lock-order witness (docs/analysis.md): opt-in via
#   HOROVOD_ANALYSIS_WITNESS=1 python -m pytest tests/... -q
# Locks created by horovod_tpu modules are instrumented for the whole
# session (armed at horovod_tpu import, above); the teardown assertion
# fails the run on any witnessed acquisition cycle.
# --------------------------------------------------------------------------
from horovod_tpu.core.config import _env_bool as _hvd_env_bool  # noqa: E402

if _hvd_env_bool("HOROVOD_ANALYSIS_WITNESS", False):
    from horovod_tpu.analysis import witness as _witness
    _witness.install()

    @pytest.fixture(scope="session", autouse=True)
    def _lock_order_witness():
        yield
        _witness.check()   # raises WitnessCycleError on a cycle
