"""Launcher unit tests: host parsing, slot assignment, CLI env mapping,
KV store, programmatic run() integration.

Mirrors the reference's test/single/test_run.py (arg parsing, backend
choice, cmdline construction) and test/integration/test_static_run.py
(real localhost launch)."""
import json
import os
import sys
import textwrap

import numpy as np
import pytest

from horovod_tpu.runner.hosts import (HostInfo, get_host_assignments,
                                      parse_host_file, parse_hosts)
from horovod_tpu.runner.http_kv import (KVStoreClient, RendezvousServer,
                                        make_secret)
from horovod_tpu.runner.launch import check_build, env_from_args, parse_args


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:2, b:4,c")
        assert hosts == [HostInfo("a", 2), HostInfo("b", 4),
                         HostInfo("c", 1)]

    def test_parse_host_file(self, tmp_path):
        p = tmp_path / "hf"
        p.write_text("# comment\nhost1 slots=2\nhost2\n")
        assert parse_host_file(str(p)) == [HostInfo("host1", 2),
                                           HostInfo("host2", 1)]

    def test_assignments_ranks(self):
        slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
        assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
                for s in slots] == [
            ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
        assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
                   for s in slots)

    def test_assignments_partial(self):
        slots = get_host_assignments(parse_hosts("a:4,b:4"), 3)
        assert [s.hostname for s in slots] == ["a", "a", "a"]
        assert slots[2].local_size == 3

    def test_np_exceeds_slots(self):
        with pytest.raises(ValueError, match="exceeds"):
            get_host_assignments(parse_hosts("a:1"), 2)


class TestCLI:
    def test_flag_env_mapping(self):
        args = parse_args(["-np", "2", "--fusion-threshold-mb", "32",
                           "--cycle-time-ms", "2.5", "--autotune",
                           "--timeline-filename", "/tmp/tl.json",
                           "python", "train.py"])
        env = env_from_args(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "2.5"
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert env["HOROVOD_TIMELINE"] == "/tmp/tl.json"
        assert args.command == ["python", "train.py"]

    def test_config_file_merge(self, tmp_path):
        cfg = tmp_path / "conf.json"
        cfg.write_text(json.dumps({"cycle-time-ms": 7.0,
                                   "cache-capacity": 99}))
        args = parse_args(["-np", "1", "--config-file", str(cfg),
                           "--cache-capacity", "5", "x"])
        env = env_from_args(args)
        assert env["HOROVOD_CYCLE_TIME"] == "7.0"   # from config file
        assert env["HOROVOD_CACHE_CAPACITY"] == "5"  # CLI wins

    def test_check_build_mentions_tpu(self):
        assert "XLA collectives" in check_build()


class TestKVStore:
    def test_put_get_roundtrip(self):
        secret = make_secret()
        server = RendezvousServer(secret=secret)
        port = server.start()
        try:
            client = KVStoreClient("127.0.0.1", port, secret)
            client.put("scope", "k1", b"hello")
            assert client.get("scope", "k1") == b"hello"
            assert client.get("scope", "missing") is None
            assert client.wait("scope", "k1") == b"hello"
        finally:
            server.stop()

    def test_bad_secret_rejected(self):
        server = RendezvousServer(secret=make_secret())
        port = server.start()
        try:
            bad = KVStoreClient("127.0.0.1", port, "wrong")
            with pytest.raises(RuntimeError, match="403"):
                bad.put("s", "k", b"x")
        finally:
            server.stop()

    def test_rendezvous_plan(self):
        server = RendezvousServer()
        port = server.start()
        try:
            slots = get_host_assignments(parse_hosts("localhost:2"), 2)
            server.init(slots)
            client = KVStoreClient("127.0.0.1", port)
            meta = json.loads(client.get("rendezvous", "meta"))
            assert meta["size"] == 2
            rec = json.loads(client.get("rendezvous", "1"))
            assert rec["rank"] == 1 and rec["local_rank"] == 1
        finally:
            server.stop()


def _worker_identity():
    import os
    return {k: os.environ.get(f"HOROVOD_{k.upper()}")
            for k in ("rank", "size", "local_rank", "cross_rank")}


class TestProgrammaticRun:
    def test_run_two_local_workers(self):
        import horovod_tpu
        results = horovod_tpu.run(_worker_identity, np=2)
        assert results[0]["rank"] == "0" and results[1]["rank"] == "1"
        assert all(r["size"] == "2" for r in results)

    def test_worker_failure_raises(self):
        import horovod_tpu
        with pytest.raises(RuntimeError, match="exited"):
            horovod_tpu.run(_fail_fn, np=2)


def _fail_fn():
    import os
    if os.environ.get("HOROVOD_RANK") == "1":
        raise SystemExit(3)
    return "ok"


class TestTpuPodMode:
    def test_detect_and_hosts_arg(self):
        from horovod_tpu.runner.tpu_pod import (detect_tpu_pod_hosts,
                                                tpu_pod_hosts_arg,
                                                tpu_worker_id)
        env = {"TPU_WORKER_HOSTNAMES": "t1v-0,t1v-1,t1v-2,t1v-3",
               "TPU_WORKER_ID": "0"}
        assert detect_tpu_pod_hosts(env) == ["t1v-0", "t1v-1", "t1v-2",
                                             "t1v-3"]
        assert tpu_pod_hosts_arg(env) == "t1v-0:1,t1v-1:1,t1v-2:1,t1v-3:1"
        assert tpu_worker_id(env) == 0
        assert detect_tpu_pod_hosts({}) is None

    def test_hvd_override_wins(self):
        from horovod_tpu.runner.tpu_pod import detect_tpu_pod_hosts
        env = {"TPU_WORKER_HOSTNAMES": "a,b",
               "HOROVOD_TPU_WORKER_HOSTNAMES": "x,y,z"}
        assert detect_tpu_pod_hosts(env) == ["x", "y", "z"]

    def test_requires_worker_zero(self):
        from horovod_tpu.runner.tpu_pod import require_worker_zero
        with pytest.raises(RuntimeError, match="worker 0"):
            require_worker_zero({"TPU_WORKER_ID": "2"})
        require_worker_zero({"TPU_WORKER_ID": "0"})   # no raise

    def test_missing_metadata_raises(self):
        from horovod_tpu.runner.tpu_pod import tpu_pod_hosts_arg
        with pytest.raises(RuntimeError, match="no TPU pod metadata"):
            tpu_pod_hosts_arg({})

    def test_launch_flag_synthesizes_hosts(self, monkeypatch):
        """--tpu-pod on worker 0 resolves the pod hosts into -H form
        before run_static sees the args."""
        from horovod_tpu.runner import launch
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-0,t1v-1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        seen = {}

        def fake_run_static(args):
            seen["hosts"] = args.hosts
            seen["hostfile"] = args.hostfile
            return 0

        monkeypatch.setattr(launch, "run_static", fake_run_static)
        rc = launch.main(["--tpu-pod", "python", "-c", "pass"])
        assert rc == 0
        assert seen["hosts"] == "t1v-0:1,t1v-1:1"
        assert seen["hostfile"] is None

    def test_launch_flag_rejects_elastic_combo(self, monkeypatch, capsys):
        from horovod_tpu.runner import launch
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-0,t1v-1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        rc = launch.main(["--tpu-pod", "--min-np", "1",
                          "python", "-c", "pass"])
        assert rc == 2
        assert "--tpu-pod is static" in capsys.readouterr().err

    def test_malformed_worker_id(self):
        from horovod_tpu.runner.tpu_pod import tpu_worker_id
        with pytest.raises(RuntimeError, match="not an integer"):
            tpu_worker_id({"TPU_WORKER_ID": "worker-0"})
        assert tpu_worker_id({"TPU_WORKER_ID": " 3 "}) == 3


class TestCliParity:
    def test_yaml_config_file(self, tmp_path):
        pytest.importorskip("yaml")
        from horovod_tpu.runner.launch import env_from_args, parse_args
        cfg = tmp_path / "conf.yaml"
        cfg.write_text("cycle-time-ms: 9.0\nfusion-threshold-mb: 2\n")
        args = parse_args(["-np", "1", "--config-file", str(cfg), "x"])
        env = env_from_args(args)
        assert env["HOROVOD_CYCLE_TIME"] == "9.0"
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(2 * 1024 * 1024)

    def test_new_flags_parse(self):
        from horovod_tpu.runner.launch import parse_args
        args = parse_args(["-np", "2", "--reset-limit", "3", "--slots", "2",
                           "-p", "2222", "-i", "/tmp/id_rsa",
                           "--output-filename", "/tmp/out", "cmd"])
        assert args.reset_limit == 3 and args.slots == 2
        assert args.ssh_port == 2222
        assert args.ssh_identity_file == "/tmp/id_rsa"
        assert args.output_filename == "/tmp/out"

    def test_ssh_command_options(self):
        from horovod_tpu.runner.exec import build_command
        from horovod_tpu.runner.hosts import SlotInfo
        slot = SlotInfo("remotehost", 0, 2, 0, 1, 0, 2)
        cmd = build_command(slot, ["echo", "hi"], {"PATH": "/usr/bin"},
                            ssh_port=2222, ssh_identity_file="/k")
        assert cmd[0] == "ssh"
        assert "-p" in cmd and "2222" in cmd
        assert "-i" in cmd and "/k" in cmd

    def test_output_filename_redirect(self, tmp_path):
        import sys
        from horovod_tpu.runner.exec import WorkerProcess
        from horovod_tpu.runner.hosts import SlotInfo
        slot = SlotInfo("localhost", 1, 2, 0, 1, 0, 1)
        w = WorkerProcess(slot, [sys.executable, "-c", "print('hello')"],
                          dict(os.environ), output_dir=str(tmp_path))
        assert w.wait(timeout=30) == 0
        assert (tmp_path / "rank.1").read_text().strip() == "hello"

    def test_host_hash_stable_and_salted(self, monkeypatch):
        from horovod_tpu.runner.hosts import host_hash
        assert host_hash() == host_hash()
        assert host_hash() != host_hash(salt=1)
        monkeypatch.setenv("HOROVOD_HOSTNAME", "nodeA")
        a = host_hash()
        monkeypatch.setenv("HOROVOD_HOSTNAME", "nodeB")
        assert a != host_hash()


class TestReferenceFlagParity:
    """VERDICT r2 item 8: the reference's documented command lines parse
    verbatim (reference horovod/runner/launch.py:286-594 and
    docs/running.rst examples)."""

    def _parse(self, argv):
        from horovod_tpu.runner.launch import parse_args
        return parse_args(argv)

    def test_reference_doc_examples_verbatim(self):
        # docs/running.rst:19,25,47
        a = self._parse("-np 4 -H localhost:4 python train.py".split())
        assert a.num_proc == 4 and a.hosts == "localhost:4"
        assert a.command == ["python", "train.py"]
        a = self._parse(
            "-np 16 -H server1:4,server2:4,server3:4,server4:4 "
            "python train.py".split())
        assert a.num_proc == 16 and a.hosts.count(":4") == 4
        a = self._parse("-np 6 -hostfile myhostfile python train.py".split())
        assert a.hostfile == "myhostfile"

    def test_gpu_era_flags_warned_and_ignored(self, capsys):
        a = self._parse(
            ["-np", "4", "--network-interfaces", "eth0,eth1",
             "--mpi-args=--oversubscribe", "--tcp",
             "--binding-args", "socket", "--num-nccl-streams", "2",
             "--thread-affinity", "8", "--mpi-threads-disable",
             "python", "train.py"])
        err = capsys.readouterr().err
        assert err.count("ignored on TPU") == 7
        assert a.command == ["python", "train.py"]
        # none of them leak into the worker env
        from horovod_tpu.runner.launch import env_from_args
        env = env_from_args(a)
        assert not any("NCCL" in k or "MPI" in k for k in env)

    def test_paired_no_flags_export_zero(self):
        from horovod_tpu.runner.launch import env_from_args
        env = env_from_args(self._parse(
            ["-np", "2", "--no-hierarchical-allreduce", "--no-autotune",
             "--no-torus-allreduce", "--no-hierarchical-allgather", "x"]))
        assert env["HOROVOD_HIERARCHICAL_ALLREDUCE"] == "0"
        assert env["HOROVOD_AUTOTUNE"] == "0"
        assert env["HOROVOD_TORUS_ALLREDUCE"] == "0"
        assert env["HOROVOD_HIERARCHICAL_ALLGATHER"] == "0"
        # unset flags export nothing (config defaults apply)
        env2 = env_from_args(self._parse(["-np", "2", "x"]))
        assert "HOROVOD_HIERARCHICAL_ALLREDUCE" not in env2

    def test_explicit_hierarchical_freezes_autotune_knob(self, monkeypatch):
        # --no-hierarchical-allreduce must prevent the tuner from
        # re-enabling it (reference launch.py:380-384)
        from horovod_tpu.core.config import Config
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "0")
        cfg = Config.from_env()
        assert cfg.hierarchical_allreduce_set and \
            not cfg.hierarchical_allreduce
        from horovod_tpu.autotune.tuner import ParameterManager
        pm = ParameterManager(tune_two_level=not (
            cfg.torus_allreduce or cfg.hierarchical_allreduce or
            cfg.hierarchical_allreduce_set))
        assert pm.two_level_allreduce is False

    def test_stall_and_autotune_reference_names(self):
        from horovod_tpu.runner.launch import env_from_args
        env = env_from_args(self._parse(
            ["-np", "2", "--stall-check-warning-time-seconds", "30",
             "--stall-check-shutdown-time-seconds", "90",
             "--no-stall-check",
             "--autotune-warmup-samples", "5",
             "--autotune-steps-per-sample", "20",
             "--autotune-bayes-opt-max-samples", "30",
             "--autotune-gaussian-process-noise", "0.9",
             "--gloo-timeout-seconds", "45",
             "--log-with-timestamp", "--disable-cache", "x"]))
        assert env["HOROVOD_STALL_CHECK_TIME_SECONDS"] == "30.0"
        assert env["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] == "90.0"
        assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
        assert env["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] == "5"
        assert env["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] == "20"
        assert env["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] == "30"
        assert env["HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"] == "0.9"
        assert env["HOROVOD_GLOO_TIMEOUT_SECONDS"] == "45.0"
        assert env["HOROVOD_LOG_WITH_TIMESTAMP"] == "1"
        assert env["HOROVOD_CACHE_CAPACITY"] == "0"

    def test_elastic_reference_aliases(self):
        a = self._parse(
            ["--min-num-proc", "2", "--max-num-proc", "8",
             "--slots-per-host", "4", "--elastic-timeout", "300",
             "--blacklist-cooldown-range", "5", "60",
             "--host-discovery-script", "./discover.sh", "python",
             "train.py"])
        assert a.min_np == 2 and a.max_np == 8 and a.slots == 4
        assert a.elastic_timeout == 300.0
        assert a.blacklist_cooldown_range == [5.0, 60.0]

    def test_cooldown_range_configures_host_state(self):
        from horovod_tpu.elastic.discovery import (HostState,
                                                   set_blacklist_cooldown_range)
        prev = (HostState.COOLDOWN_BASE, HostState.COOLDOWN_MAX)
        try:
            set_blacklist_cooldown_range(2.0, 30.0)
            assert HostState.COOLDOWN_BASE == 2.0
            assert HostState.COOLDOWN_MAX == 30.0
            with pytest.raises(ValueError):
                set_blacklist_cooldown_range(10.0, 1.0)
        finally:
            HostState.COOLDOWN_BASE, HostState.COOLDOWN_MAX = prev

    def test_version_flag(self, capsys):
        import horovod_tpu
        from horovod_tpu.runner.launch import parse_args
        with pytest.raises(SystemExit) as e:
            parse_args(["--version"])
        assert e.value.code == 0
        assert horovod_tpu.__version__ in capsys.readouterr().out

    def test_config_file_cli_precedence(self, tmp_path):
        # CLI wins over config file (reference config_parser contract)
        import json as _json
        from horovod_tpu.runner.launch import env_from_args
        cfg = tmp_path / "conf.json"
        cfg.write_text(_json.dumps({"cycle-time-ms": 9.0,
                                    "cache-capacity": 77}))
        env = env_from_args(self._parse(
            ["-np", "1", "--config-file", str(cfg),
             "--cycle-time-ms", "3.0", "x"]))
        assert env["HOROVOD_CYCLE_TIME"] == "3.0"      # CLI wins
        assert env["HOROVOD_CACHE_CAPACITY"] == "77"   # file fills gap


def test_every_reference_flag_parses(capsys):
    """Final flag audit (VERDICT r3 item 8): every add_argument name in
    the reference horovodrun CLI (horovod/runner/launch.py:286-594)
    parses here — implemented, aliased, or warn-and-ignored. The list is
    the complete reference flag-name inventory, kept literal so the test
    runs without the reference checkout."""
    from horovod_tpu.runner.launch import parse_args

    # flags taking a value (flag, sample) — one parse each
    valued = [
        ("-np", "2"), ("--num-proc", "2"),
        ("--start-timeout", "30"),
        ("--network-interfaces", "eth0,eth1"),
        ("--network-interface", "eth0"),
        ("--output-filename", "/tmp/o"),
        ("--config-file", None),          # needs a real file; parse-only skip
        ("-p", "12"), ("--ssh-port", "12"),
        ("-i", "/tmp/id"), ("--ssh-identity-file", "/tmp/id"),
        ("--fusion-threshold-mb", "64"), ("--cycle-time-ms", "5"),
        ("--cache-capacity", "1024"),
        ("--autotune-log-file", "/tmp/a"),
        ("--autotune-warmup-samples", "3"),
        ("--autotune-steps-per-sample", "10"),
        ("--autotune-bayes-opt-max-samples", "20"),
        ("--autotune-gaussian-process-noise", "0.8"),
        ("--min-np", "1"), ("--min-num-proc", "1"),
        ("--max-np", "4"), ("--max-num-proc", "4"),
        ("--slots-per-host", "2"),
        ("--elastic-timeout", "600"), ("--reset-limit", "3"),
        ("--blacklist-cooldown-range", None),  # nargs=2, below
        ("--timeline-filename", "/tmp/t"),
        ("--stall-check-warning-time-seconds", "60"),
        ("--stall-check-shutdown-time-seconds", "120"),
        ("--mpi-args", "-x FOO"), ("--binding-args", "-bind-to core"),
        ("--num-nccl-streams", "2"), ("--thread-affinity", "1"),
        ("--gloo-timeout-seconds", "30"),
        ("--log-level", "INFO"),
        ("-H", "localhost:2"), ("--hosts", "localhost:2"),
        ("-hostfile", "/tmp/hf"), ("--hostfile", "/tmp/hf"),
        ("--host-discovery-script", "/tmp/d.sh"),
    ]
    for flag, sample in valued:
        if sample is None:
            continue
        parse_args(["-np", "2", flag, sample, "python", "x.py"]) \
            if flag not in ("-np", "--num-proc") else \
            parse_args([flag, sample, "python", "x.py"])
    parse_args(["-np", "2", "--blacklist-cooldown-range", "10", "100",
                "python", "x.py"])

    # boolean/no-arg flags (every reference store_true/deprecated pair)
    for flag in [
            "--disable-cache", "--verbose",
            "--hierarchical-allreduce", "--no-hierarchical-allreduce",
            "--hierarchical-allgather", "--no-hierarchical-allgather",
            "--torus-allreduce", "--no-torus-allreduce",
            "--autotune", "--no-autotune",
            "--timeline-mark-cycles", "--no-timeline-mark-cycles",
            "--no-stall-check", "--stall-check",
            "--mpi-threads-disable", "--no-mpi-threads-disable",
            "--tcp",
            "--log-with-timestamp", "--log-without-timestamp",
            "-prefix-timestamp", "--prefix-output-with-timestamp",
            "--log-hide-timestamp", "--no-log-hide-timestamp",
            "--gloo", "--mpi", "--jsrun"]:
        parse_args(["-np", "2", flag, "python", "x.py"])
    capsys.readouterr()               # swallow the warn-and-ignore notes
