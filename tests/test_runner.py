"""Launcher unit tests: host parsing, slot assignment, CLI env mapping,
KV store, programmatic run() integration.

Mirrors the reference's test/single/test_run.py (arg parsing, backend
choice, cmdline construction) and test/integration/test_static_run.py
(real localhost launch)."""
import json
import os
import sys
import textwrap

import numpy as np
import pytest

from horovod_tpu.runner.hosts import (HostInfo, get_host_assignments,
                                      parse_host_file, parse_hosts)
from horovod_tpu.runner.http_kv import (KVStoreClient, RendezvousServer,
                                        make_secret)
from horovod_tpu.runner.launch import check_build, env_from_args, parse_args


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:2, b:4,c")
        assert hosts == [HostInfo("a", 2), HostInfo("b", 4),
                         HostInfo("c", 1)]

    def test_parse_host_file(self, tmp_path):
        p = tmp_path / "hf"
        p.write_text("# comment\nhost1 slots=2\nhost2\n")
        assert parse_host_file(str(p)) == [HostInfo("host1", 2),
                                           HostInfo("host2", 1)]

    def test_assignments_ranks(self):
        slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
        assert [(s.hostname, s.rank, s.local_rank, s.cross_rank)
                for s in slots] == [
            ("a", 0, 0, 0), ("a", 1, 1, 0), ("b", 2, 0, 1), ("b", 3, 1, 1)]
        assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
                   for s in slots)

    def test_assignments_partial(self):
        slots = get_host_assignments(parse_hosts("a:4,b:4"), 3)
        assert [s.hostname for s in slots] == ["a", "a", "a"]
        assert slots[2].local_size == 3

    def test_np_exceeds_slots(self):
        with pytest.raises(ValueError, match="exceeds"):
            get_host_assignments(parse_hosts("a:1"), 2)


class TestCLI:
    def test_flag_env_mapping(self):
        args = parse_args(["-np", "2", "--fusion-threshold-mb", "32",
                           "--cycle-time-ms", "2.5", "--autotune",
                           "--timeline-filename", "/tmp/tl.json",
                           "python", "train.py"])
        env = env_from_args(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "2.5"
        assert env["HOROVOD_AUTOTUNE"] == "1"
        assert env["HOROVOD_TIMELINE"] == "/tmp/tl.json"
        assert args.command == ["python", "train.py"]

    def test_config_file_merge(self, tmp_path):
        cfg = tmp_path / "conf.json"
        cfg.write_text(json.dumps({"cycle-time-ms": 7.0,
                                   "cache-capacity": 99}))
        args = parse_args(["-np", "1", "--config-file", str(cfg),
                           "--cache-capacity", "5", "x"])
        env = env_from_args(args)
        assert env["HOROVOD_CYCLE_TIME"] == "7.0"   # from config file
        assert env["HOROVOD_CACHE_CAPACITY"] == "5"  # CLI wins

    def test_check_build_mentions_tpu(self):
        assert "XLA collectives" in check_build()


class TestKVStore:
    def test_put_get_roundtrip(self):
        secret = make_secret()
        server = RendezvousServer(secret=secret)
        port = server.start()
        try:
            client = KVStoreClient("127.0.0.1", port, secret)
            client.put("scope", "k1", b"hello")
            assert client.get("scope", "k1") == b"hello"
            assert client.get("scope", "missing") is None
            assert client.wait("scope", "k1") == b"hello"
        finally:
            server.stop()

    def test_bad_secret_rejected(self):
        server = RendezvousServer(secret=make_secret())
        port = server.start()
        try:
            bad = KVStoreClient("127.0.0.1", port, "wrong")
            with pytest.raises(RuntimeError, match="403"):
                bad.put("s", "k", b"x")
        finally:
            server.stop()

    def test_rendezvous_plan(self):
        server = RendezvousServer()
        port = server.start()
        try:
            slots = get_host_assignments(parse_hosts("localhost:2"), 2)
            server.init(slots)
            client = KVStoreClient("127.0.0.1", port)
            meta = json.loads(client.get("rendezvous", "meta"))
            assert meta["size"] == 2
            rec = json.loads(client.get("rendezvous", "1"))
            assert rec["rank"] == 1 and rec["local_rank"] == 1
        finally:
            server.stop()


def _worker_identity():
    import os
    return {k: os.environ.get(f"HOROVOD_{k.upper()}")
            for k in ("rank", "size", "local_rank", "cross_rank")}


class TestProgrammaticRun:
    def test_run_two_local_workers(self):
        import horovod_tpu
        results = horovod_tpu.run(_worker_identity, np=2)
        assert results[0]["rank"] == "0" and results[1]["rank"] == "1"
        assert all(r["size"] == "2" for r in results)

    def test_worker_failure_raises(self):
        import horovod_tpu
        with pytest.raises(RuntimeError, match="exited"):
            horovod_tpu.run(_fail_fn, np=2)


def _fail_fn():
    import os
    if os.environ.get("HOROVOD_RANK") == "1":
        raise SystemExit(3)
    return "ok"


class TestTpuPodMode:
    def test_detect_and_hosts_arg(self):
        from horovod_tpu.runner.tpu_pod import (detect_tpu_pod_hosts,
                                                tpu_pod_hosts_arg,
                                                tpu_worker_id)
        env = {"TPU_WORKER_HOSTNAMES": "t1v-0,t1v-1,t1v-2,t1v-3",
               "TPU_WORKER_ID": "0"}
        assert detect_tpu_pod_hosts(env) == ["t1v-0", "t1v-1", "t1v-2",
                                             "t1v-3"]
        assert tpu_pod_hosts_arg(env) == "t1v-0:1,t1v-1:1,t1v-2:1,t1v-3:1"
        assert tpu_worker_id(env) == 0
        assert detect_tpu_pod_hosts({}) is None

    def test_hvd_override_wins(self):
        from horovod_tpu.runner.tpu_pod import detect_tpu_pod_hosts
        env = {"TPU_WORKER_HOSTNAMES": "a,b",
               "HOROVOD_TPU_WORKER_HOSTNAMES": "x,y,z"}
        assert detect_tpu_pod_hosts(env) == ["x", "y", "z"]

    def test_requires_worker_zero(self):
        from horovod_tpu.runner.tpu_pod import require_worker_zero
        with pytest.raises(RuntimeError, match="worker 0"):
            require_worker_zero({"TPU_WORKER_ID": "2"})
        require_worker_zero({"TPU_WORKER_ID": "0"})   # no raise

    def test_missing_metadata_raises(self):
        from horovod_tpu.runner.tpu_pod import tpu_pod_hosts_arg
        with pytest.raises(RuntimeError, match="no TPU pod metadata"):
            tpu_pod_hosts_arg({})

    def test_launch_flag_synthesizes_hosts(self, monkeypatch):
        """--tpu-pod on worker 0 resolves the pod hosts into -H form
        before run_static sees the args."""
        from horovod_tpu.runner import launch
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-0,t1v-1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        seen = {}

        def fake_run_static(args):
            seen["hosts"] = args.hosts
            seen["hostfile"] = args.hostfile
            return 0

        monkeypatch.setattr(launch, "run_static", fake_run_static)
        rc = launch.main(["--tpu-pod", "python", "-c", "pass"])
        assert rc == 0
        assert seen["hosts"] == "t1v-0:1,t1v-1:1"
        assert seen["hostfile"] is None

    def test_launch_flag_rejects_elastic_combo(self, monkeypatch, capsys):
        from horovod_tpu.runner import launch
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-0,t1v-1")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        rc = launch.main(["--tpu-pod", "--min-np", "1",
                          "python", "-c", "pass"])
        assert rc == 2
        assert "--tpu-pod is static" in capsys.readouterr().err

    def test_malformed_worker_id(self):
        from horovod_tpu.runner.tpu_pod import tpu_worker_id
        with pytest.raises(RuntimeError, match="not an integer"):
            tpu_worker_id({"TPU_WORKER_ID": "worker-0"})
        assert tpu_worker_id({"TPU_WORKER_ID": " 3 "}) == 3


class TestCliParity:
    def test_yaml_config_file(self, tmp_path):
        pytest.importorskip("yaml")
        from horovod_tpu.runner.launch import env_from_args, parse_args
        cfg = tmp_path / "conf.yaml"
        cfg.write_text("cycle-time-ms: 9.0\nfusion-threshold-mb: 2\n")
        args = parse_args(["-np", "1", "--config-file", str(cfg), "x"])
        env = env_from_args(args)
        assert env["HOROVOD_CYCLE_TIME"] == "9.0"
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(2 * 1024 * 1024)

    def test_new_flags_parse(self):
        from horovod_tpu.runner.launch import parse_args
        args = parse_args(["-np", "2", "--reset-limit", "3", "--slots", "2",
                           "-p", "2222", "-i", "/tmp/id_rsa",
                           "--output-filename", "/tmp/out", "cmd"])
        assert args.reset_limit == 3 and args.slots == 2
        assert args.ssh_port == 2222
        assert args.ssh_identity_file == "/tmp/id_rsa"
        assert args.output_filename == "/tmp/out"

    def test_ssh_command_options(self):
        from horovod_tpu.runner.exec import build_command
        from horovod_tpu.runner.hosts import SlotInfo
        slot = SlotInfo("remotehost", 0, 2, 0, 1, 0, 2)
        cmd = build_command(slot, ["echo", "hi"], {"PATH": "/usr/bin"},
                            ssh_port=2222, ssh_identity_file="/k")
        assert cmd[0] == "ssh"
        assert "-p" in cmd and "2222" in cmd
        assert "-i" in cmd and "/k" in cmd

    def test_output_filename_redirect(self, tmp_path):
        import sys
        from horovod_tpu.runner.exec import WorkerProcess
        from horovod_tpu.runner.hosts import SlotInfo
        slot = SlotInfo("localhost", 1, 2, 0, 1, 0, 1)
        w = WorkerProcess(slot, [sys.executable, "-c", "print('hello')"],
                          dict(os.environ), output_dir=str(tmp_path))
        assert w.wait(timeout=30) == 0
        assert (tmp_path / "rank.1").read_text().strip() == "hello"

    def test_host_hash_stable_and_salted(self, monkeypatch):
        from horovod_tpu.runner.hosts import host_hash
        assert host_hash() == host_hash()
        assert host_hash() != host_hash(salt=1)
        monkeypatch.setenv("HOROVOD_HOSTNAME", "nodeA")
        a = host_hash()
        monkeypatch.setenv("HOROVOD_HOSTNAME", "nodeB")
        assert a != host_hash()
