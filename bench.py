#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic img/sec (data-parallel).

TPU-native analog of the reference's synthetic benchmark
(/root/reference/examples/pytorch/pytorch_synthetic_benchmark.py): random
image batches through ResNet-50 with the DistributedOptimizer train step,
img/sec reported over timed iterations.

Prints ONE JSON line:
  {"metric": "resnet50_synthetic_img_sec_per_chip", "value": N,
   "unit": "img/sec/chip", "vs_baseline": N}

vs_baseline compares per-chip throughput against the reference's documented
tf_cnn_benchmarks ResNet-101 example output (1656.82 img/sec on 16 P100s =
103.55 img/sec/GPU, /root/reference/docs/benchmarks.rst:30-42) — the only
quantitative throughput figure the reference publishes.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.resnet import ResNet50
from horovod_tpu.training import (init_replicated, make_train_step,
                                  shard_batch)

BASELINE_IMG_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:30-42


def main():
    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    n_dev = hvd.size()
    platform = jax.devices()[0].platform

    # Per-chip batch sized for one v5e chip in bf16; smaller on CPU so the
    # harness still runs in CI.
    per_chip_batch = 64 if platform == "tpu" else 2
    batch = per_chip_batch * n_dev
    image_size = 224 if platform == "tpu" else 64
    num_warmup = 2 if platform != "tpu" else 4
    num_iters = 3 if platform != "tpu" else 10

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    variables = model.init(rng, dummy, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    tx = optax.sgd(0.01, momentum=0.9)
    params = init_replicated(params, mesh)
    batch_stats = init_replicated(batch_stats, mesh)
    step = make_train_step(model.apply, tx, mesh, has_batch_stats=True)
    opt_state = init_replicated(step.init_opt_state(params), mesh)

    images = shard_batch(
        np.random.rand(batch, image_size, image_size, 3).astype(np.float32),
        mesh)
    labels = shard_batch(
        np.random.randint(0, 1000, size=(batch,)).astype(np.int32), mesh)

    for _ in range(num_warmup):
        params, opt_state, batch_stats, loss = step(
            params, opt_state, batch_stats, images, labels)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(num_iters):
        params, opt_state, batch_stats, loss = step(
            params, opt_state, batch_stats, images, labels)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_sec = batch * num_iters / dt
    img_sec_per_chip = img_sec / n_dev
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(img_sec_per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_sec_per_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
