#!/usr/bin/env python
"""Headline benchmark: ResNet-50 synthetic img/sec (data-parallel).

TPU-native analog of the reference's synthetic benchmark
(/root/reference/examples/pytorch/pytorch_synthetic_benchmark.py): random
image batches through ResNet-50 with the DistributedOptimizer train step,
img/sec reported over timed iterations.

Prints ONE JSON line:
  {"metric": "resnet50_synthetic_img_sec_per_chip", "value": N,
   "unit": "img/sec/chip", "vs_baseline": N}

HVD_BENCH_MODEL selects resnet50 (default) | resnet101 | vgg16 |
inception3 — the reference's full headline scaling trio
(docs/benchmarks.rst:8-13) plus the rebuild's flagship.

`--metrics` (or HVD_BENCH_METRICS=1) folds step-time p50/p99 from the
obs registry's histogram into the summary line and prints the end-of-run
registry snapshot as a second JSON line (docs/metrics.md).

`--serve` runs the serving ACCEPTANCE GATE (slotted vs paged+prefix vs
speculative over a shared-system-prompt overload burst; bit-identity,
paged-speedup bar (HVD_BENCH_SERVE_SPEEDUP_BAR, default 1.25 since the
round-6 last_idx baseline speedup), token-bounded KV,
TTFT/jit-flat/spec bars all
asserted — exit nonzero on violation — plus the decode-KERNEL bars:
the full configuration on pallas vs xla, parity gated everywhere,
speed gated on TPU, docs/serving.md), `--kernel-parity` the standalone
pallas==xla token-stream gate ({GPT, Llama-GQA} x {greedy, spec,
sampled}),
`--serve-soak` the chaos-hardened fleet soak (serve_p99_under_fault_ms
+ failover_ms from a seeded crash/partition/corrupt/slow incident,
now paged+prefix+speculative by default —
docs/serving.md), `--serve-fleet` the MULTI-PROCESS fleet loopback
(fleet_failover_ms + degraded-capacity shed rate from real replica
worker processes under a seeded SIGKILL + dispatch blips —
docs/serving.md process-fleet section), `--ckpt`
the checkpoint-plane loopback (ckpt_save_ms / ckpt_blocking_ms /
ckpt_restore_ms — docs/checkpoint.md), `--collectives` the
collective-algorithm microbench (bytes/s per algorithm x tensor size
plus the measured crossover table — docs/benchmarks.md), `--converge`
the convergence-matrix gate (every runnable wire-format x op x
algorithm cell trained to its documented tolerance, rejected cells
asserted fail-fast — docs/benchmarks.md convergence section), and
`--redist`
the redistribution microbench (redist_ms / redist_bytes_per_s for an
in-memory N->M vs the ckpt save+restore round trip, plus
weight_swap_ms for a serve hot-swap — docs/redistribution.md), each
emitting the same one-JSON-line-per-metric format.

vs_baseline compares per-chip throughput against the reference's documented
tf_cnn_benchmarks ResNet-101 example output (1656.82 img/sec on 16 P100s =
103.55 img/sec/GPU, /root/reference/docs/benchmarks.rst:30-42) — the only
quantitative throughput figure the reference publishes.

Resilience: the TPU tunnel in this environment is flaky, so backend init is
retried with backoff in a fresh subprocess each attempt (a hung PJRT client
cannot be recovered in-process), and any terminal failure is reported as a
structured JSON error line rather than a traceback.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:30-42

#: per-attempt budget; generous for first-compile (~20-40s) + timed iters
ATTEMPT_TIMEOUT_S = int(os.environ.get("HVD_BENCH_ATTEMPT_TIMEOUT", "420"))
MAX_ATTEMPTS = int(os.environ.get("HVD_BENCH_ATTEMPTS", "3"))
BACKOFF_S = 20.0
#: overall deadline: when the TPU tunnel is hard-down every attempt burns
#: its full timeout, and the driver's own timeout must not fire before we
#: emit the structured error line
MAX_TOTAL_S = int(os.environ.get("HVD_BENCH_TOTAL_TIMEOUT", "600"))

_MARK = "HVD_BENCH_RESULT:"
#: --metrics: the worker prints the end-of-run registry snapshot on this
#: marker line and the driver forwards it verbatim
_MARK_METRICS = "HVD_BENCH_METRICS:"

#: mirror of horovod_tpu.models.bench_zoo.BENCH_MODELS — kept literal so
#: main() never imports the package (and thus jax) in the parent process;
#: tests/test_models.py asserts the two stay identical
_BENCH_MODELS = ("resnet18", "resnet50", "resnet101", "vgg16", "inception3")


def run_benchmark():
    """The measured body. Runs in a worker subprocess; prints the result
    JSON prefixed with _MARK on success."""
    import jax

    # Persistent compilation cache: the dominant cost of a bench attempt on
    # a healthy tunnel is the first ResNet-50 compile (~20-40s, sometimes
    # much longer over a slow relay). With the cache warm, any later tunnel
    # window costs seconds, so retries and driver re-runs stop burning
    # their whole 420s budget recompiling. min thresholds are 0 so even
    # cheap executables (the init fns) persist.
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # older jax without the persistent cache knobs
        pass

    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import (init_replicated, make_train_step,
                                      shard_batch)

    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    n_dev = hvd.size()
    platform = jax.devices()[0].platform

    # HVD_BENCH_MODEL extends the harness to the rest of the reference's
    # headline trio (docs/benchmarks.rst:8-13: Inception V3 / ResNet-101 /
    # VGG-16). The driver headline stays resnet50. Model construction /
    # sizing policy lives in models/bench_zoo.py (shared with
    # examples/synthetic_benchmark.py).
    from horovod_tpu.models.bench_zoo import (build_benchmark_model,
                                              default_image_size)
    model_name = os.environ.get("HVD_BENCH_MODEL", "resnet50")
    # Per-chip batch sized for one v5e chip in bf16; smaller on CPU so the
    # harness still runs in CI.
    heavy = model_name in ("vgg16", "inception3", "resnet101")
    # B=32 per chip: an on-hardware sweep (docs/benchmarks.md round-3
    # record) measured 16/32/48/64/128 and found the old default 64 the
    # WORST point (2.3k img/s vs 2.6-2.8k for 32-128)
    per_chip_batch = 32 if platform == "tpu" else (1 if heavy else 2)
    # HVD_BENCH_BATCH overrides the per-chip batch (sweep support; the
    # default operating point was chosen by an on-hardware sweep)
    if os.environ.get("HVD_BENCH_BATCH"):
        per_chip_batch = int(os.environ["HVD_BENCH_BATCH"])
    batch = per_chip_batch * n_dev
    image_size = default_image_size(model_name, platform == "tpu")
    num_warmup = 2 if platform != "tpu" else 4
    # Two timed runs of different lengths: per-step time is taken from the
    # SLOPE between them, which cancels the fixed host<->device readback
    # latency. On the tunneled TPU in this environment block_until_ready
    # returns before device execution finishes, so each timed run must end
    # with a real scalar readback (float(loss)) to observe completion.
    # Inline copy of benchmarks/_timing.slope_time — kept standalone so the
    # driver can run bench.py in isolation; keep the two in sync.
    num_iters_a = 2 if platform != "tpu" else 10
    num_iters_b = 6 if platform != "tpu" else 30

    # HVD_BENCH_STEM=space_to_depth selects the MXU-friendly blocked stem
    # (models/resnet.py); default stays the classic conv7
    stem = os.environ.get("HVD_BENCH_STEM", "conv7")
    apply_fn, params, batch_stats, has_bn = build_benchmark_model(
        model_name, image_size, stem=stem)

    tx = optax.sgd(0.01, momentum=0.9)
    params = init_replicated(params, mesh)
    batch_stats = init_replicated(batch_stats, mesh)
    step = make_train_step(apply_fn, tx, mesh, has_batch_stats=has_bn)
    opt_state = init_replicated(step.init_opt_state(params), mesh)

    images = shard_batch(
        np.random.rand(batch, image_size, image_size, 3).astype(np.float32),
        mesh)
    labels = shard_batch(
        np.random.randint(0, 1000, size=(batch,)).astype(np.int32), mesh)

    for _ in range(num_warmup):
        params, opt_state, batch_stats, loss = step(
            params, opt_state, batch_stats, images, labels)
    float(loss)  # readback: wait for device execution

    def timed(n):
        nonlocal params, opt_state, batch_stats
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, batch_stats, loss = step(
                params, opt_state, batch_stats, images, labels)
        float(loss)  # scalar readback — the only reliable completion fence
        return time.perf_counter() - t0

    # Each timed run repeats HVD_BENCH_REPEATS times and keeps the MIN:
    # host/relay noise only ever ADDS time, and a one-off stall inside the
    # short run would otherwise shrink the slope and inflate img/s.
    repeats = int(os.environ.get("HVD_BENCH_REPEATS",
                                 "2" if platform == "tpu" else "1"))
    dt_a = min(timed(num_iters_a) for _ in range(repeats))
    dt_b = min(timed(num_iters_b) for _ in range(repeats))
    step_time = (dt_b - dt_a) / (num_iters_b - num_iters_a)
    timing = "slope"
    if step_time <= 0:  # timing noise on very fast runs: fall back to mean
        step_time = dt_b / num_iters_b
        timing = "mean_fallback"  # latency-biased; marked so readers know

    # --metrics: one extra observed pass with per-step readback, each
    # step timed into the obs registry's step-time histogram, so the
    # summary line carries p50/p99 and the snapshot shows the engine
    # counters (wire bytes, cycles) for the whole run. Separate from
    # the slope-timed runs above: per-step readback serializes the
    # pipeline and would bias the throughput figure.
    step_pcts = {}
    if os.environ.get("HVD_BENCH_METRICS") == "1":
        from horovod_tpu import obs
        for _ in range(num_iters_a):
            with obs.step_timer():
                params, opt_state, batch_stats, loss = step(
                    params, opt_state, batch_stats, images, labels)
                float(loss)
        hist = obs.get_registry().get("hvd_step_time_ms")
        if hist is not None and hist.count:
            step_pcts = {
                "step_ms_p50": round(hist.percentile(0.50), 3),
                "step_ms_p99": round(hist.percentile(0.99), 3)}
        print(_MARK_METRICS + json.dumps(obs.get_registry().snapshot()),
              flush=True)

    img_sec = batch / step_time
    img_sec_per_chip = img_sec / n_dev
    # wire_bytes_per_step: gradient-allreduce payload per step per chip
    # under each wire format (fp32 native vs int8 block-scaled payload +
    # scale sidecar, optim/compression.py wire_bytes) so BENCH_*.json
    # tracks bytes-on-wire alongside img/s
    from horovod_tpu.optim.compression import wire_bytes as _wire_bytes
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(params))
    block = hvd.core.basics.get_config().compression_block_size
    wire_per_step = {
        "fp32": _wire_bytes(n_params, "none", itemsize=4),
        "int8": _wire_bytes(n_params, "int8", block),
    }
    # the published figure is ResNet-101 img/sec/GPU — only the resnets
    # compare meaningfully against it
    vs_base = round(img_sec_per_chip / BASELINE_IMG_SEC_PER_CHIP, 3) \
        if model_name.startswith("resnet") else None
    print(_MARK + json.dumps({
        "metric": f"{model_name}_synthetic_img_sec_per_chip",
        "value": round(img_sec_per_chip, 2),
        "unit": "img/sec/chip",
        "vs_baseline": vs_base,
        "platform": platform,
        "n_devices": n_dev,
        "timing": timing,
        "stem": stem,
        "batch": per_chip_batch,
        "repeats": repeats,
        "wire_bytes_per_step": wire_per_step,
        **step_pcts,
    }), flush=True)


def run_serve_soak_benchmark() -> int:
    """Serving-soak benchmark (`bench.py --serve-soak`): run the
    chaos-hardened fleet soak (horovod_tpu/serve/soak.py — N replicas,
    closed-loop traffic, seeded crash/partition/corrupt/slow plan) and
    print TWO JSON metric lines — serve_p99_under_fault_ms (p99 request
    latency OUTSIDE the bounded recovery windows, i.e. the latency a
    client sees on a bad day once failover has done its job) and
    failover_ms (replica death -> ejection + in-flight re-enqueued).
    Exits non-zero when the soak verdict itself is red."""
    try:
        from horovod_tpu.serve.soak import run_serve_soak
        replicas = int(os.environ.get("HVD_BENCH_SOAK_REPLICAS", "3"))
        clients = int(os.environ.get("HVD_BENCH_SOAK_CLIENTS", "6"))
        seed = int(os.environ.get("HVD_BENCH_SOAK_SEED", "7"))
        verdict = run_serve_soak(replicas=replicas, clients=clients,
                                 seed=seed)
        common = {"replicas": replicas, "clients": clients,
                  "seed": seed, "soak_ok": verdict["ok"],
                  "error_rate_outside": verdict["error_rate_outside"],
                  "submitted": verdict["submitted"],
                  "wall_s": verdict["wall_s"]}
        fo_ms = None if verdict.get("failover_s") is None \
            else round(verdict["failover_s"] * 1000.0, 1)
        print(json.dumps({
            "metric": "serve_p99_under_fault_ms",
            "value": verdict["p99_outside_ms"], "unit": "ms",
            **common}), flush=True)
        print(json.dumps({
            "metric": "failover_ms", "value": fo_ms, "unit": "ms",
            **common}), flush=True)
        return 0 if verdict["ok"] else 1
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric in ("serve_p99_under_fault_ms", "failover_ms"):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": "ms", "error": str(e)[-500:]}),
                  flush=True)
        return 1


def run_fleet_benchmark() -> int:
    """Multi-process fleet benchmark (`bench.py --serve-fleet`): run
    the PROCESS-fleet soak (horovod_tpu/serve/soak.py run_fleet_soak —
    real replica worker processes, a seeded SIGKILL of one worker plus
    conn_reset/flaky blips on the dispatch wire) and print JSON metric
    lines from the real-process loopback:

    * ``fleet_failover_ms`` — worker SIGKILL -> accrual ejection +
      in-flight re-enqueued (the O(heartbeat) detection bound, across
      a REAL process boundary);
    * ``fleet_shed_rate_degraded`` — the fraction of requests shed
      (always with retry_after_ms, capacity-scaled) while the fleet
      ran at degraded capacity — graceful degradation, quantified;
    * ``fleet_dispatch_absorbed`` — transient dispatch blips absorbed
      by the retry ladder with zero failovers.

    Exits non-zero when the soak verdict itself is red."""
    try:
        from horovod_tpu.serve.soak import run_fleet_soak
        replicas = int(os.environ.get("HVD_BENCH_FLEET_REPLICAS", "2"))
        clients = int(os.environ.get("HVD_BENCH_FLEET_CLIENTS", "4"))
        seed = int(os.environ.get("HVD_BENCH_FLEET_SEED", "7"))
        verdict = run_fleet_soak(replicas=replicas, clients=clients,
                                 seed=seed)
        # shed rate while degraded: sheds over submissions inside the
        # window from the first ejection to the victim's re-admission
        evs = []
        try:
            with open(os.path.join(verdict["out_dir"],
                                   "events.jsonl")) as f:
                evs = [json.loads(x) for x in f if x.strip()]
            with open(os.path.join(verdict["out_dir"],
                                   "requests.jsonl")) as f:
                reqs = [json.loads(x) for x in f if x.strip()]
        except OSError:
            reqs = []
        t0 = next((e["t"] for e in evs if e.get("event") == "eject"),
                  None)
        t1 = next((e["t"] for e in evs if e.get("event") == "readmit"),
                  None)
        shed_rate = None
        if t0 is not None and t1 is not None and reqs:
            # request records and events both carry wall-clock stamps
            inside = [r for r in reqs if t0 <= r["t0"] <= t1]
            if inside:
                shed = [r for r in inside
                        if r["status"] in ("shed", "rejected")]
                shed_rate = round(len(shed) / len(inside), 4)
        common = {"replicas": replicas, "clients": clients,
                  "seed": seed, "soak_ok": verdict["ok"],
                  "failovers": verdict["fleet"]["failovers"],
                  "respawns": verdict["fleet"]["respawns"],
                  "submitted": verdict["submitted"],
                  "wall_s": verdict["wall_s"]}
        fo_ms = None if verdict.get("failover_s") is None \
            else round(verdict["failover_s"] * 1000.0, 1)
        print(json.dumps({
            "metric": "fleet_failover_ms", "value": fo_ms,
            "unit": "ms", **common}), flush=True)
        print(json.dumps({
            "metric": "fleet_shed_rate_degraded", "value": shed_rate,
            "unit": "fraction", **common}), flush=True)
        print(json.dumps({
            "metric": "fleet_dispatch_absorbed",
            "value": verdict["dispatch_absorbed"], "unit": "count",
            **common}), flush=True)
        return 0 if verdict["ok"] else 1
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric, unit in (("fleet_failover_ms", "ms"),
                             ("fleet_shed_rate_degraded", "fraction"),
                             ("fleet_dispatch_absorbed", "count")):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": unit, "error": str(e)[-500:]}),
                  flush=True)
        return 1


def run_disagg_benchmark() -> int:
    """Disaggregation acceptance GATE (`bench.py --serve-disagg`):
    p99 TTFT under mixed long-prompt/short-decode overload —
    DISAGGREGATED pools (1 prefill + 1 decode worker process,
    serve/disagg.py) vs the COLOCATED process fleet (2 workers,
    serve/proc_fleet.py) at matched process count, matched model,
    matched traffic.

    Traffic: enough closed-loop background clients to keep every
    COLOCATED row/block busy with long-prompt + long-decode
    generations (the head-of-line pressure: a colocated replica's
    rows and pool blocks are held hostage for a WHOLE generation, so
    a new prompt waits out someone else's decode tail before it can
    even prefill), while a probe stream submits short 1-token
    requests whose e2e latency IS time-to-first-token in both
    systems. In the disaggregated fleet probes resolve entirely in
    the prefill pool — whose rows turn over at prefill+migrate speed,
    never held for a generation — which is exactly the DistServe
    separation claim, measured.

    Gate (exit nonzero on violation, each verdict a JSON line):

      * p99 TTFT ratio disagg/colocated <=
        HVD_BENCH_DISAGG_TTFT_BAR (default 1.0 — disaggregation must
        BEAT colocated under this overload);
      * zero silent drops on BOTH sides: every submitted request
        reached a terminal state (sheds carry retry_after_ms);
      * the disagg leg actually migrated (long requests crossed
        pools) and answered its long requests.
    """
    import threading

    import numpy as np

    try:
        from horovod_tpu.native.store import StoreServer
        from horovod_tpu.serve.disagg import DisaggRouter
        from horovod_tpu.serve.proc_fleet import ProcessFleetRouter
        from horovod_tpu.serve.queue import Rejected

        bar = float(os.environ.get("HVD_BENCH_DISAGG_TTFT_BAR", "1.0"))
        duration_s = float(os.environ.get(
            "HVD_BENCH_DISAGG_DURATION_S", "12"))
        # 8 long clients x (24-token prompt + 24-token budget) pin all
        # 2x4 colocated rows (and their worst-case block
        # reservations) for whole generations — the overload the
        # split exists for
        n_long = int(os.environ.get("HVD_BENCH_DISAGG_LONG_CLIENTS",
                                    "8"))
        long_len, long_new = 24, 24
        worker = {
            "builder": "horovod_tpu.serve.worker:tiny_gpt_builder",
            "builder_kwargs": {"seed": 0, "paged": True,
                               "kv_pool_blocks": 48},
            "buckets": [8, 32], "max_queue": 64,
            "deadline_ms": 20000.0, "kv_crc": False, "spec_k": 0,
            "prefix_cache": False}
        # per-pool sizing is the POINT of disaggregation: the prefill
        # worker is provisioned for admission throughput (wide batch,
        # rows turn over at prefill+migrate speed; parked sequences
        # stage here while decode capacity frees), the decode worker
        # for resident capacity — total chip-equivalent budget stays
        # comparable to the 2-worker colocated fleet
        prefill_worker = dict(worker, builder_kwargs={
            "seed": 0, "paged": True, "max_batch": 8,
            "kv_pool_blocks": 96})

        def drive(router) -> dict:
            stop = threading.Event()
            lock = threading.Lock()
            probes, longs = [], []

            def long_client(cid):
                rng = np.random.RandomState(100 + cid)
                while not stop.is_set():
                    prompt = list(rng.randint(1, 64, long_len))
                    try:
                        h = router.submit(prompt,
                                          max_new_tokens=long_new)
                    except Rejected as e:
                        with lock:
                            longs.append("shed")
                        time.sleep(min((e.retry_after_ms or 100.0),
                                       300.0) / 1000.0)
                        continue
                    h.wait(timeout=25.0)
                    with lock:
                        longs.append(h.status if h.done()
                                     else "pending")

            def probe_client():
                rng = np.random.RandomState(999)
                while not stop.is_set():
                    prompt = list(rng.randint(1, 64, 4))
                    t0 = time.monotonic()
                    try:
                        h = router.submit(prompt, max_new_tokens=1)
                    except Rejected:
                        with lock:
                            probes.append(("shed", None))
                        time.sleep(0.1)
                        continue
                    h.wait(timeout=25.0)
                    ms = (time.monotonic() - t0) * 1000.0
                    with lock:
                        probes.append((h.status if h.done()
                                       else "pending", ms))
                    time.sleep(0.04)

            threads = [threading.Thread(target=long_client, args=(c,),
                                        daemon=True)
                       for c in range(n_long)]
            threads.append(threading.Thread(target=probe_client,
                                            daemon=True))
            for t in threads:
                t.start()
            time.sleep(duration_s)
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
            oks = sorted(ms for st, ms in probes
                         if st == "ok" and ms is not None)
            p99 = (oks[min(len(oks) - 1, int(0.99 * len(oks)))]
                   if len(oks) >= 20 else None)
            return {
                "probe_p99_ms": None if p99 is None else round(p99, 1),
                "probe_ok": len(oks),
                "probe_statuses": {
                    s: sum(1 for st, _ in probes if st == s)
                    for s in {st for st, _ in probes}},
                "long_statuses": {
                    s: longs.count(s) for s in set(longs)},
                "silent_drops": (
                    sum(1 for st, _ in probes if st == "pending")
                    + longs.count("pending")),
            }

        srv = StoreServer()
        try:
            colo = ProcessFleetRouter(
                2, kv_addr="127.0.0.1", kv_port=srv.port,
                worker=worker, ns="benchcolo", suspect_s=3.0).start()
            try:
                colo_r = drive(colo)
            finally:
                colo.close()
            dis = DisaggRouter(
                1, 1, kv_addr="127.0.0.1", kv_port=srv.port,
                prefill_worker=prefill_worker, decode_worker=worker,
                ns="benchdis", suspect_s=3.0).start()
            try:
                dis_r = drive(dis)
                migrations = int(
                    dis.stats().get("migrate_bytes") or 0)
            finally:
                dis.close()
        finally:
            srv.close()

        ratio = None
        if colo_r["probe_p99_ms"] and dis_r["probe_p99_ms"]:
            ratio = round(dis_r["probe_p99_ms"]
                          / colo_r["probe_p99_ms"], 3)
        gates = {
            "ttft_ratio_under_bar": ratio is not None
            and ratio <= bar,
            "no_silent_drops": (colo_r["silent_drops"] == 0
                                and dis_r["silent_drops"] == 0),
            "migrations_happened": migrations > 0,
            "longs_answered": dis_r["long_statuses"].get("ok", 0) > 0,
        }
        common = {"bar": bar, "duration_s": duration_s,
                  "long_clients": n_long,
                  "colocated": colo_r, "disagg": dis_r,
                  "migrate_bytes": migrations, "gates": gates}
        print(json.dumps({
            "metric": "disagg_ttft_p99_ms",
            "value": dis_r["probe_p99_ms"], "unit": "ms", **common}),
            flush=True)
        print(json.dumps({
            "metric": "disagg_ttft_ratio_vs_colocated",
            "value": ratio, "unit": "ratio", **common}), flush=True)
        return 0 if all(gates.values()) else 1
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric, unit in (("disagg_ttft_p99_ms", "ms"),
                             ("disagg_ttft_ratio_vs_colocated",
                              "ratio")):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": unit, "error": str(e)[-500:]}),
                  flush=True)
        return 1


def run_autoscale_benchmark() -> int:
    """Autoscale acceptance GATE (`bench.py --autoscale`): the full
    loop — signals -> policy -> actuator — driven end to end on a 1+1
    disaggregated fleet under phased bursty traffic (the chaos-free
    autoscale soak, serve/soak.py run_autoscale_soak), with the
    verdict asserted rather than just reported.

    Gate (exit nonzero on violation, each verdict a JSON line):

      * capacity tracked load: BOTH pools scaled up under the
        long-prompt burst and back down in the cool phase;
      * p99 TTFT SLO held outside the planned disruption windows
        (<= HVD_BENCH_AUTOSCALE_P99_MS, default 15000);
      * zero silent drops and answered-exactly-once across every
        scale event (drains requeue, newcomers dedupe);
      * every newcomer admitted on the newest streamed weight version
        (the respawn gate, generalized to scale-up).
    """
    try:
        from horovod_tpu.serve.soak import run_autoscale_soak

        slo = float(os.environ.get("HVD_BENCH_AUTOSCALE_P99_MS",
                                   "15000"))
        duration = float(os.environ.get(
            "HVD_BENCH_AUTOSCALE_DURATION_S", "240"))
        v = run_autoscale_soak(None, plan=None, slo_p99_ms=slo,
                               max_duration_s=duration)
        gates = {
            "capacity_tracks_load": bool(v.get("scaled_up")
                                         and v.get("scaled_down")),
            "ttft_slo_held": v.get("slo_held") is True,
            "no_silent_drops": (v.get("no_silent_drops") is True
                                and v.get("answered_once") is True),
            "newcomers_on_newest":
                v.get("newcomers_on_newest") is True,
        }
        events = v.get("scale_events") or {}
        common = {"slo_p99_ms": slo, "scale_events": events,
                  "statuses": v.get("statuses"), "gates": gates,
                  "wall_s": v.get("wall_s"),
                  "out_dir": v.get("out_dir")}
        print(json.dumps({
            "metric": "autoscale_ttft_p99_outside_ms",
            "value": v.get("p99_outside_ms"), "unit": "ms",
            **common}), flush=True)
        print(json.dumps({
            "metric": "autoscale_scale_events",
            "value": sum(c.get("up", 0) + c.get("down", 0)
                         for c in events.values()),
            "unit": "events", **common}), flush=True)
        return 0 if all(gates.values()) else 1
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric, unit in (("autoscale_ttft_p99_outside_ms", "ms"),
                             ("autoscale_scale_events", "events")):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": unit, "error": str(e)[-500:]}),
                  flush=True)
        return 1


def run_serve_benchmark() -> int:
    """Serving acceptance GATE (`bench.py --serve`): the ROADMAP item 2
    bars, asserted — not just reported. One workload (a long shared
    system prompt + short unique tails, submitted as a 2x-overload
    burst) is driven through three configurations of the continuous
    batcher over one tiny GPT decoder:

      slotted            the PR 2 baseline layout (slots x max_len)
      paged+prefix       HOROVOD_SERVE_KV_BLOCK + _PREFIX_CACHE on
      paged+prefix+spec  ... + HOROVOD_SERVE_SPEC_K (drafter attached)

    and the gate asserts (exit nonzero on any violation, each verdict
    printed as a JSON line):

      * bit-identical output: every configuration emits exactly the
        slotted greedy baseline's tokens (same tokens, same stops);
      * speedup: paged+prefix tokens/s >= HVD_BENCH_SERVE_SPEEDUP_BAR
        (default 1.25) x slotted on this shared-prefix workload — the
        bar was 1.5 until round 6's last_idx logits restriction sped
        the slotted BASELINE's prefill (every absolute number
        improved; the ratio honestly shrank);
      * tokens/s floor: the full configuration sustains >=
        HVD_BENCH_SERVE_TOKS_BAR tok/s per chip;
      * memory: peak KV tokens RESIDENT in the paged pool stay under
        a bound computed from tokens actually touched — and under the
        slotted layout's slots x max_len worst case (which the paged
        pool is provisioned below by construction);
      * p99 TTFT under the 2x-overload burst <=
        HVD_BENCH_SERVE_TTFT_P99_MS, with zero expiries/errors;
      * jit-cache-flat: the admission churn of the overload burst adds
        zero compiled programs after warmup in every configuration;
      * speculation: < 0.7 target-model steps per generated token
        (machine-independent), acceptance rate exported via obs;
      * tracing overhead: the full configuration with the tracing
        plane armed (a per-request context, every batcher record site
        live) emits bit-identical tokens, stays within
        HVD_BENCH_SERVE_TRACE_OVERHEAD (default 3%) of untraced
        tokens/s, and adds zero compiled programs.

    Keeps emitting serve_tokens_per_s / serve_p50_ms (now for the full
    configuration) so the bench trajectory stays comparable."""
    import numpy as np

    try:
        import jax
        import jax.numpy as jnp

        from horovod_tpu.core.config import Config
        from horovod_tpu.models.gpt import GPT, GPTConfig
        from horovod_tpu.obs import metrics as obs_metrics
        from horovod_tpu.serve import (AdmissionQueue, ContinuousBatcher,
                                       ShardedExecutor)

        cfg = Config.from_env()
        platform = jax.devices()[0].platform
        n_req = int(os.environ.get("HVD_BENCH_SERVE_REQUESTS", "32"))
        toks_bar = float(os.environ.get("HVD_BENCH_SERVE_TOKS_BAR", "25"))
        # paged-vs-slotted ratio bar. Recalibrated 1.5 -> 1.25 in round
        # 6: the last_idx logits restriction (serve/executor.py) cut
        # the SLOTTED baseline's per-prefill cost by the whole
        # [B, bucket, V] lm_head + argmax (~30-50% on this tiny-vocab
        # bench model), so the ratio shrank while every absolute
        # number improved (slotted 700->1050 tok/s class on the CPU
        # container; paged+prefix unchanged ~1370). The absolute floor
        # (toks_bar) and the token-bounded-KV gate still ratchet the
        # layout's value; this bar guards the prefix cache's RELATIVE
        # win on the shared-prompt workload.
        speedup_bar = float(os.environ.get(
            "HVD_BENCH_SERVE_SPEEDUP_BAR", "1.25"))
        ttft_bar_ms = float(os.environ.get(
            "HVD_BENCH_SERVE_TTFT_P99_MS", "5000"))
        max_batch = cfg.serve_max_batch
        # prefill-dominated on purpose: the speedup under test is
        # "shared system prompts computed once", so the workload keeps
        # the generation tail short and the shared prefix long
        sys_len, tail_max, max_new, spec_k = 160, 8, 4, 3
        max_len = 192
        buckets = (8, 168)
        # the three knobs ARE the configuration under test: block size
        # from HOROVOD_SERVE_KV_BLOCK (default 8 for the tiny bench
        # model), spec depth from HOROVOD_SERVE_SPEC_K, prefix cache
        # forced on for the paged phases
        block = cfg.serve_kv_block or 8
        spec_k = cfg.serve_spec_k or spec_k
        from horovod_tpu.serve import pool_blocks_for
        pool_blocks = pool_blocks_for(cfg.serve_max_batch, max_len,
                                      block)
        kw = dict(vocab_size=256, num_layers=2, num_heads=4, head_dim=16,
                  max_seq_len=max_len,
                  dtype=jnp.bfloat16 if platform == "tpu" else jnp.float32,
                  attention_impl=None if platform == "tpu" else "reference")
        params = GPT(GPTConfig(**kw)).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]

        # the workload: one long system prompt shared by every request,
        # each with a short unique tail — the shape the radix cache is
        # for. The PRIME request warms the prefix cache (production
        # serves a standing system prompt); the burst is 2x-overload
        # high concurrency: all n_req land at once on max_batch rows.
        rng = np.random.RandomState(0)
        system = list(rng.randint(0, 256, sys_len))
        prompts = [system + list(rng.randint(0, 256,
                                             rng.randint(4, tail_max + 1)))
                   for _ in range(n_req)]
        prime = system + list(rng.randint(0, 256, tail_max))

        def drive(paged, prefix, spec, kernel="xla", traced=False):
            from horovod_tpu.trace.context import TraceContext

            def _trace():
                # a fresh wire-form context per request: every record
                # site in the batcher goes live, exactly the armed-
                # tracing cost a traced fleet pays per request
                return (TraceContext.mint().to_wire() if traced
                        else None)
            mcfg = GPTConfig(decode=True, **kw,
                             kv_block_size=block if paged else 0,
                             kv_pool_blocks=pool_blocks if paged else 0,
                             decode_kernel=kernel if paged else None)
            ex = ShardedExecutor(GPT(mcfg), params, max_batch=max_batch,
                                 max_len=max_len)
            draft = None
            if spec:
                draft = ShardedExecutor(
                    GPT(GPTConfig(decode=True, **kw)), params,
                    max_batch=max_batch, max_len=max_len, role="draft")
            q = AdmissionQueue(max_queue=max(cfg.serve_max_queue,
                                             n_req + 1),
                               default_deadline_ms=cfg.serve_deadline_ms)
            b = ContinuousBatcher(ex, q, buckets=buckets,
                                  prefix_cache=prefix,
                                  draft_executor=draft, spec_k=spec_k)
            b.warmup()
            jit0 = ex.jit_cache_size()
            q.submit(prime, max_new_tokens=max_new, trace=_trace())
            b.run()                      # prime: publishes the prefix run
            # best-of-2 bursts: one shared-machine hiccup must not turn
            # a real 2x layout win into a flaky gate verdict
            wall, handles = None, None
            for _ in range(2):
                t0 = time.perf_counter()
                hs = [q.submit(p, max_new_tokens=max_new,
                               trace=_trace())
                      for p in prompts]
                b.run()
                dt = time.perf_counter() - t0
                bad = [h.status for h in hs if h.status != "ok"]
                if bad:
                    raise RuntimeError(
                        f"burst requests failed under the gate: {bad[:5]}")
                if wall is None or dt < wall:
                    wall = dt
                if handles is None:
                    handles = hs
            ttft = obs_metrics.get_registry().get("hvd_serve_ttft_ms")
            return {
                "tokens": [h.tokens for h in handles],
                "tok_s": sum(len(h.tokens) for h in handles) / wall,
                "p50_ms": sorted(h.latency_ms for h in handles)[
                    len(handles) // 2],
                "ttft_p99_ms": (ttft.percentile(0.99)
                                if ttft is not None and ttft.count
                                else None),
                "jit_flat": ex.jit_cache_size() == jit0,
                "peak_tokens": (b.kv.pool.peak_in_use * block
                                if paged else max_batch * max_len),
                "prefix_hits": b.prefix.hits if b.prefix else 0,
                "tokens_saved": (b.prefix.tokens_saved
                                 if b.prefix else 0),
                "steps_per_token": (b.gen_steps / b.gen_tokens
                                    if b.gen_tokens else None),
            }

        slotted = drive(False, False, False)
        paged = drive(True, True, False)
        full = drive(True, True, True)
        # kernel bars: the identical full configuration on the fused
        # Pallas kernels (compiled on TPU; interpret mode on CPU —
        # an EMULATOR, so off-TPU the speed ratio only documents the
        # emulation cost and the gate asserts PARITY, not speed)
        full_pallas = drive(True, True, True, kernel="pallas")
        # tracing armed: identical full configuration, every batcher
        # record site live with a per-request context — the tracing
        # plane's overhead gate (docs/tracing.md)
        trace_bar = float(os.environ.get(
            "HVD_BENCH_SERVE_TRACE_OVERHEAD", "0.03"))
        full_traced = drive(True, True, True, traced=True)

        accept = obs_metrics.get_registry().get(
            "hvd_serve_spec_accept_rate")
        speedup = paged["tok_s"] / slotted["tok_s"]
        kernel_speedup = full_pallas["tok_s"] / full["tok_s"]
        trace_ratio = full_traced["tok_s"] / full["tok_s"]
        # tokens-resident bound: the shared prefix run plus each row's
        # private tail+generation+speculative-margin blocks, with 1.5x
        # slack for re-prefills/CoW — far under slots x max_len
        bs = block
        per_row = -(-(tail_max + max_new + spec_k + 1) // bs) + 1
        token_bound = 1.5 * ((-(-len(prime) // bs)) * bs
                             + max_batch * per_row * bs)
        slot_bound = max_batch * max_len
        gates = {
            "bit_identical_paged": paged["tokens"] == slotted["tokens"],
            "bit_identical_spec": full["tokens"] == slotted["tokens"],
            "speedup_ge_bar": speedup >= speedup_bar,
            "tokens_per_s_ge_bar": full["tok_s"] >= toks_bar,
            "kv_peak_bounded_by_tokens":
                paged["peak_tokens"] <= token_bound < slot_bound
                and full["peak_tokens"] <= token_bound,
            "ttft_p99_under_2x_overload":
                full["ttft_p99_ms"] is not None
                and full["ttft_p99_ms"] <= ttft_bar_ms,
            "jit_cache_flat": (slotted["jit_flat"] and paged["jit_flat"]
                               and full["jit_flat"]),
            "spec_steps_per_token_lt_0p7":
                full["steps_per_token"] is not None
                and full["steps_per_token"] < 0.7,
            "spec_accept_rate_exported":
                accept is not None and accept.count > 0,
            # the Pallas path must emit the identical token stream;
            # the tokens/s ratchet is asserted only where the kernel
            # actually compiles (TPU) — interpret mode is an emulator
            "kernel_parity": full_pallas["tokens"] == slotted["tokens"],
            "kernel_jit_flat": full_pallas["jit_flat"],
            **({"kernel_speedup_ge_1": kernel_speedup >= 1.0}
               if platform == "tpu" else {}),
            # tracing must be free where it matters: identical
            # tokens, tokens/s within the overhead bar, zero new
            # compiled programs (spans never touch traced jax code)
            "trace_bit_identical":
                full_traced["tokens"] == slotted["tokens"],
            "trace_overhead_within_bar":
                trace_ratio >= 1.0 - trace_bar,
            "trace_jit_flat": full_traced["jit_flat"],
        }
        common = {"platform": platform, "requests": n_req,
                  "max_batch": max_batch, "system_prompt_len": sys_len,
                  "max_new_tokens": max_new, "spec_k": spec_k,
                  "kv_block": block, "kv_pool_blocks": pool_blocks}
        if os.environ.get("HVD_BENCH_METRICS") == "1":
            from horovod_tpu import obs
            hist = obs.get_registry().get(
                "hvd_serve_step_ms",
                {"kind": "decode", "kernel": "pallas"})
            if hist is not None and hist.count:
                common["step_ms_p50"] = round(hist.percentile(0.50), 3)
                common["step_ms_p99"] = round(hist.percentile(0.99), 3)
            print(json.dumps({"metric": "metrics_snapshot",
                              "value": obs.get_registry().snapshot()}),
                  flush=True)
        print(json.dumps({
            "metric": "serve_tokens_per_s",
            "value": round(full["tok_s"], 2), "unit": "tok/s",
            "slotted_tokens_per_s": round(slotted["tok_s"], 2),
            "paged_prefix_tokens_per_s": round(paged["tok_s"], 2),
            **common}), flush=True)
        print(json.dumps({
            "metric": "serve_p50_ms",
            "value": round(full["p50_ms"], 2), "unit": "ms",
            **common}), flush=True)
        print(json.dumps({
            "metric": "serve_paged_speedup",
            "value": round(speedup, 3), "unit": "x", "bar": speedup_bar,
            "prefix_hits": paged["prefix_hits"],
            "prefix_tokens_saved": paged["tokens_saved"],
            **common}), flush=True)
        print(json.dumps({
            "metric": "serve_kv_peak_tokens",
            "value": paged["peak_tokens"], "unit": "tokens",
            "token_bound": int(token_bound),
            "slots_x_max_len": slot_bound, **common}), flush=True)
        print(json.dumps({
            "metric": "serve_ttft_p99_ms",
            "value": (None if full["ttft_p99_ms"] is None
                      else round(full["ttft_p99_ms"], 1)),
            "unit": "ms", "bar": ttft_bar_ms, **common}), flush=True)
        print(json.dumps({
            "metric": "serve_kernel_speedup",
            "value": round(kernel_speedup, 3), "unit": "x",
            "pallas_tokens_per_s": round(full_pallas["tok_s"], 2),
            "xla_tokens_per_s": round(full["tok_s"], 2),
            "pallas_ttft_p99_ms": (
                None if full_pallas["ttft_p99_ms"] is None
                else round(full_pallas["ttft_p99_ms"], 1)),
            "xla_ttft_p99_ms": (None if full["ttft_p99_ms"] is None
                                else round(full["ttft_p99_ms"], 1)),
            "gated_on_speed": platform == "tpu",
            **common}), flush=True)
        print(json.dumps({
            "metric": "serve_trace_overhead",
            "value": round(1.0 - trace_ratio, 4), "unit": "fraction",
            "bar": trace_bar,
            "traced_tokens_per_s": round(full_traced["tok_s"], 2),
            "untraced_tokens_per_s": round(full["tok_s"], 2),
            **common}), flush=True)
        print(json.dumps({
            "metric": "serve_spec_steps_per_token",
            "value": (None if full["steps_per_token"] is None
                      else round(full["steps_per_token"], 3)),
            "unit": "steps/tok", "bar": 0.7,
            "accept_rate_samples": int(accept.count) if accept else 0,
            **common}), flush=True)
        print(json.dumps({"metric": "serve_gate",
                          "value": all(gates.values()),
                          "gates": gates, **common}), flush=True)
        if not all(gates.values()):
            return 1
        return 0
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric, unit in (("serve_tokens_per_s", "tok/s"),
                             ("serve_p50_ms", "ms")):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": unit, "error": str(e)[-500:]}),
                  flush=True)
        return 1


def run_kvtier_benchmark() -> int:
    """Fleet-KV-tier acceptance GATE (`bench.py --kv-tier`): prove the
    eviction ladder EARNS its bytes — a returning conversation whose
    prefix runs were demoted to the DISK rung (the slowest one: 0 MiB
    host ring, every demotion spills to an hvdkv-v1 file) must still
    beat recomputing the prefix from scratch. One tiny GPT decoder,
    two identically-driven stacks:

      tier       paged + prefix + kv_tier (host ring 0 -> disk spill)
      re-prefill paged + prefix, NO tier (evicted runs just die)

    Each trial: serve the first turn of a long conversation (the
    prefix cache inserts its runs), evict EVERY refcount-zero run
    (tier: demote to disk; baseline: die), then serve the returning
    turn and time it. Gates (exit nonzero, JSON verdict lines):

      * returning-turn latency: best-of-N tier <=
        HVD_BENCH_KVTIER_TTFT_RATIO (default 0.95) x best-of-N
        re-prefill — promotion must beat recompute even from disk;
      * promotion actually happened (> 0 blocks on every tier trial —
        a win that came from anything else is not this gate's win);
      * bit-identical tokens: tier first-turn AND returning-turn
        tokens equal the no-tier stack's exactly;
      * crc ledger intact: zero corrupt promotions detected, and every
        spill file left on disk re-verifies (per-leaf crc32);
      * jit cache flat: demote/promote churn adds zero compiled
        programs after the warm trial in both stacks.
    """
    import numpy as np

    try:
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.gpt import GPT, GPTConfig
        from horovod_tpu.serve import (AdmissionQueue, ContinuousBatcher,
                                       ShardedExecutor, pool_blocks_for)
        from horovod_tpu.serve.kvtier.tier import (TierEntry,
                                                   read_spill_file)

        platform = jax.devices()[0].platform
        trials = int(os.environ.get("HVD_BENCH_KVTIER_TRIALS", "3"))
        ratio_bar = float(os.environ.get(
            "HVD_BENCH_KVTIER_TTFT_RATIO", "0.95"))
        sys_len, tail_len, max_new = 160, 4, 4
        max_len, block, max_batch = 192, 8, 4
        buckets = (8, 176)
        kw = dict(vocab_size=256, num_layers=2, num_heads=4,
                  head_dim=16, max_seq_len=max_len,
                  dtype=jnp.bfloat16 if platform == "tpu"
                  else jnp.float32,
                  attention_impl=None if platform == "tpu"
                  else "reference")
        params = GPT(GPTConfig(**kw)).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))["params"]
        pool_blocks = pool_blocks_for(max_batch, max_len, block)
        rng = np.random.RandomState(0)
        first_turn = list(rng.randint(0, 256, sys_len + tail_len))

        import tempfile
        spill_root = tempfile.mkdtemp(prefix="hvd-kvtier-bench-")

        def build(tier: bool):
            mcfg = GPTConfig(decode=True, **kw, kv_block_size=block,
                             kv_pool_blocks=pool_blocks)
            ex = ShardedExecutor(GPT(mcfg), params,
                                 max_batch=max_batch, max_len=max_len)
            q = AdmissionQueue(max_queue=16,
                               default_deadline_ms=60000.0)
            b = ContinuousBatcher(
                ex, q, buckets=buckets, prefix_cache=True,
                kv_crc=True, kv_tier=tier, kvtier_host_mb=0,
                kvtier_dir=(os.path.join(spill_root, "tier")
                            if tier else None))
            b.warmup()
            return ex, q, b

        def evict_all(b) -> int:
            n = 0
            while b.prefix.evictable_blocks() > 0:
                got = b.prefix.evict(64)
                if not got:
                    break
                n += got
            return n

        def drive(tier: bool):
            ex, q, b = build(tier)
            h = q.submit(first_turn, max_new_tokens=max_new)
            b.run()
            if h.status != "ok":
                raise RuntimeError(
                    f"first turn failed: {h.status} {h.error}")
            first_tokens = list(h.tokens)
            returning = first_turn + first_tokens + [7]
            walls, promoted_each, ret_tokens = [], [], None
            jit0 = None
            # trial 0 warms the returning-turn bucket; jit flatness is
            # asserted over the MEASURED trials
            for t in range(trials + 1):
                evict_all(b)
                t0 = time.perf_counter()
                h2 = q.submit(returning, max_new_tokens=max_new)
                b.run()
                dt = (time.perf_counter() - t0) * 1000.0
                if h2.status != "ok":
                    raise RuntimeError(
                        f"returning turn failed: {h2.status} {h2.error}")
                if ret_tokens is None:
                    ret_tokens = list(h2.tokens)
                elif list(h2.tokens) != ret_tokens:
                    raise RuntimeError(
                        "returning turn tokens changed across trials")
                if t == 0:
                    jit0 = ex.jit_cache_size()
                    if tier and b.kvtier is not None:
                        promoted0 = b.kvtier.promoted_blocks
                    continue
                walls.append(dt)
                if tier and b.kvtier is not None:
                    promoted_each.append(
                        b.kvtier.promoted_blocks - promoted0)
                    promoted0 = b.kvtier.promoted_blocks
            out = {
                "first_tokens": first_tokens,
                "ret_tokens": ret_tokens,
                "best_ms": min(walls),
                "walls_ms": [round(w, 2) for w in walls],
                "jit_flat": ex.jit_cache_size() == jit0,
                "promoted_each": promoted_each,
                "corrupt_detected": (b.kvtier.corrupt_detected
                                     if tier and b.kvtier is not None
                                     else 0),
                "tier_stats": (b.kvtier.stats()
                               if tier and b.kvtier is not None
                               else None),
            }
            return out

        tier = drive(True)
        base = drive(False)

        # every spill file still on disk must re-verify its ledger
        spill_ok, spill_files = True, 0
        tier_dir = os.path.join(spill_root, "tier")
        if os.path.isdir(tier_dir):
            for name in os.listdir(tier_dir):
                if not name.endswith(".hvdkv"):
                    continue
                spill_files += 1
                header, payload = read_spill_file(
                    os.path.join(tier_dir, name))
                leaf_bytes, off = [], 0
                for n in header["nbytes"]:
                    leaf_bytes.append(payload[off:off + int(n)])
                    off += int(n)
                ent = TierEntry(header["tokens"], leaf_bytes,
                                header["crcs"], header["filled"],
                                header.get("weights_version"))
                if not ent.verify():
                    spill_ok = False

        ratio = tier["best_ms"] / base["best_ms"]
        gates = {
            "returning_beats_reprefill": ratio <= ratio_bar,
            "promoted_every_trial": (len(tier["promoted_each"]) > 0
                                     and all(p > 0 for p in
                                             tier["promoted_each"])),
            "bit_identical_first":
                tier["first_tokens"] == base["first_tokens"],
            "bit_identical_returning":
                tier["ret_tokens"] == base["ret_tokens"],
            "crc_ledger_intact":
                tier["corrupt_detected"] == 0 and spill_ok,
            "jit_cache_flat": tier["jit_flat"] and base["jit_flat"],
        }
        common = {"platform": platform, "trials": trials,
                  "kv_block": block, "first_turn_len": len(first_turn),
                  "max_new_tokens": max_new}
        print(json.dumps({
            "metric": "kvtier_returning_ttft_ms",
            "value": round(tier["best_ms"], 2), "unit": "ms",
            "reprefill_ms": round(base["best_ms"], 2),
            "ratio": round(ratio, 3), "bar": ratio_bar,
            "tier_walls_ms": tier["walls_ms"],
            "reprefill_walls_ms": base["walls_ms"],
            **common}), flush=True)
        print(json.dumps({
            "metric": "kvtier_promoted_blocks",
            "value": tier["promoted_each"], "unit": "blocks/trial",
            "spill_files_left": spill_files,
            "tier": tier["tier_stats"], **common}), flush=True)
        print(json.dumps({"metric": "kvtier_gate",
                          "value": all(gates.values()),
                          "gates": gates, **common}), flush=True)
        import shutil
        shutil.rmtree(spill_root, ignore_errors=True)
        if not all(gates.values()):
            return 1
        return 0
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        print(json.dumps({"metric": "kvtier_gate", "value": None,
                          "error": str(e)[-500:]}), flush=True)
        return 1


def run_kernel_parity() -> int:
    """`bench.py --kernel-parity`: assert the fused Pallas serving
    kernels emit TOKEN STREAMS identical to the XLA oracle across the
    matrix {GPT, Llama-GQA} x {greedy, speculative, sampled} on the
    full paged+prefix stack (interpret mode off TPU — the same parity
    tier the tier-1 suite guards, here as a standalone CI/bench gate).
    One JSON verdict line per cell; exit nonzero on any mismatch."""
    try:
        import numpy as np
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.gpt import GPT, GPTConfig
        from horovod_tpu.models.llama import Llama, LlamaConfig
        from horovod_tpu.serve import (AdmissionQueue,
                                       ContinuousBatcher,
                                       ShardedExecutor)

        platform = jax.devices()[0].platform
        block, pool = 4, 48
        ok_all = True

        def family(name):
            if name == "gpt":
                kw = dict(vocab_size=64, num_layers=2, num_heads=4,
                          head_dim=8, max_seq_len=48, dtype=jnp.float32,
                          attention_impl=None if platform == "tpu"
                          else "reference")
                mk = lambda **d: GPT(GPTConfig(**kw, **d))  # noqa: E731
            else:
                kw = dict(vocab_size=64, num_layers=2, num_heads=4,
                          num_kv_heads=2, head_dim=8, max_seq_len=48,
                          dtype=jnp.float32,
                          attention_impl=None if platform == "tpu"
                          else "reference")
                mk = lambda **d: Llama(LlamaConfig(**kw, **d))  # noqa: E731
            params = mk().init(jax.random.PRNGKey(0),
                               jnp.zeros((2, 8), jnp.int32))["params"]
            return mk, params

        def drive(mk, params, kernel, spec, sampling):
            ex = ShardedExecutor(
                mk(decode=True, kv_block_size=block,
                   kv_pool_blocks=pool, decode_kernel=kernel),
                params, max_batch=4, max_len=48)
            draft = ShardedExecutor(mk(decode=True), params,
                                    max_batch=4, max_len=48,
                                    role="draft") if spec else None
            q = AdmissionQueue(max_queue=32)
            b = ContinuousBatcher(ex, q, buckets=(8, 16),
                                  prefix_cache=True,
                                  draft_executor=draft, spec_k=3)
            b.warmup()
            # varied, mostly-divergent prompts (one fixed stream per
            # CELL so xla/pallas see identical inputs): shared-prefix
            # rows would all hit the radix cache and under-exercise
            # divergent block tables
            prng = np.random.RandomState(5)
            prompts = [list(prng.randint(0, 64, 2 + (i % 6)))
                       for i in range(8)]
            hs = [q.submit(p, max_new_tokens=5, **(sampling or {}))
                  for p in prompts]
            b.run()
            assert all(h.status == "ok" for h in hs)
            return [h.tokens for h in hs]

        sampled = dict(temperature=0.8, top_p=0.9, seed=11)
        for fam_name in ("gpt", "llama"):
            mk, params = family(fam_name)
            for mode, spec, samp in (("greedy", False, None),
                                     ("spec", True, None),
                                     ("sampled", False, sampled)):
                xla = drive(mk, params, "xla", spec, samp)
                pal = drive(mk, params, "pallas", spec, samp)
                ok = xla == pal
                ok_all = ok_all and ok
                print(json.dumps({
                    "metric": "serve_kernel_parity", "model": fam_name,
                    "mode": mode, "value": ok,
                    "platform": platform}), flush=True)
        print(json.dumps({"metric": "serve_kernel_parity_gate",
                          "value": ok_all}), flush=True)
        return 0 if ok_all else 1
    except Exception as e:  # noqa: BLE001 — structured error
        print(json.dumps({"metric": "serve_kernel_parity_gate",
                          "value": None, "error": str(e)[-500:]}),
              flush=True)
        return 1


def run_collectives_benchmark() -> int:
    """Collective-algorithm microbench (`bench.py --collectives`):
    sweeps every runnable allreduce algorithm (ops/algo.py registry —
    direct / rs_ag / rhd / two_level) across latency-bound-small to
    bandwidth-bound-large tensor sizes and emits measured bytes/s per
    (algorithm x size) as JSON lines, plus one crossover-table summary
    line comparing the per-regime MEASURED best (what the autotuner
    converges to) against the two previous fixed paths: flat psum
    ("direct" everywhere) and the all-or-nothing two-level toggle. This
    is how the algorithm-selection claim is measured, not asserted
    (docs/benchmarks.md algorithm-selection section)."""
    # a 1-device platform has no collectives to measure — force a
    # multi-device host mesh on CPU (the conftest discipline)
    ndev = int(os.environ.get("HVD_BENCH_COLL_DEVICES", "8"))
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") and ndev > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ndev}").strip()
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu.ops import algo as algo_mod
        from horovod_tpu.ops import collective_ops as co

        hvd.init()
        n = hvd.size()
        platform = jax.devices()[0].platform
        from horovod_tpu.core.mesh import mesh_is_multiprocess
        mesh_mp = mesh_is_multiprocess(hvd.core.basics.get_mesh())
        hier = hvd.core.basics.get_hier_mesh()
        hier_ok = hier is not None and hier.devices.size == n and \
            hier.devices.shape[1] > 1
        # sweep everything runnable-when-FORCED, including a degenerate
        # cross==1 hierarchy (the sweep measures; only auto-selection
        # excludes it)
        algos = list(algo_mod.runnable_algorithms(
            n, tuple(hier.devices.shape) if hier_ok else None,
            require_cross=False))
        sizes = [int(s) for s in os.environ.get(
            "HVD_BENCH_COLL_SIZES", "4096,262144,4194304").split(",")]
        iters = int(os.environ.get("HVD_BENCH_COLL_ITERS", "8"))
        trials = int(os.environ.get("HVD_BENCH_COLL_TRIALS", "5"))
        rng = np.random.RandomState(0)
        table = []
        for size in sizes:
            elems = max(size // 4, n)
            x = jnp.asarray(rng.randn(n, elems).astype(np.float32))
            best = {}
            # warmup (compile) every algorithm first so trials interleave
            for a in algos:
                jax.block_until_ready(co.allreduce(x, hvd.Sum, algo=a))
            for _ in range(trials):
                for a in algos:
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        r = co.allreduce(x, hvd.Sum, algo=a)
                    jax.block_until_ready(r)
                    dt = (time.perf_counter() - t0) / iters
                    best[a] = min(best.get(a, float("inf")), dt)
            nbytes = elems * 4
            row = {"size_bytes": nbytes,
                   "bytes_per_s": {a: round(nbytes / best[a], 1)
                                   for a in algos},
                   "model_pick": algo_mod.select_algorithm(
                       nbytes, n,
                       hier_shape=tuple(hier.devices.shape)
                       if hier_ok else None,
                       dcn=mesh_mp),
                   "measured_best": min(best, key=best.get)}
            for a in algos:
                print(json.dumps({
                    "metric": "collective_bytes_per_s", "value":
                        round(nbytes / best[a], 1), "unit": "B/s",
                    "collective": "allreduce", "algo": a,
                    "size_bytes": nbytes, "platform": platform,
                    "n_devices": n}), flush=True)
            table.append(row)
        # crossover summary: the per-regime measured best vs each
        # previous FIXED path (flat direct everywhere / two-level
        # everywhere when available)
        fixed = ["direct"] + (["two_level"] if hier_ok else [])
        summary = []
        for row in table:
            bw = row["bytes_per_s"]
            sel = row["measured_best"]
            entry = {"size_bytes": row["size_bytes"], "selected": sel,
                     "model_pick": row["model_pick"],
                     "selected_bytes_per_s": bw[sel]}
            for f in fixed:
                entry[f"win_vs_fixed_{f}"] = round(bw[sel] / bw[f], 3)
            summary.append(entry)
        print(json.dumps({
            "metric": "collective_algo_crossover", "value": summary,
            "unit": "table", "platform": platform, "n_devices": n,
            "algorithms": algos,
            "crossover_bytes_model": algo_mod.crossover_bytes(
                n, dcn=mesh_mp)}), flush=True)
        hvd.shutdown()
        return 0
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        print(json.dumps({"metric": "collective_bytes_per_s",
                          "value": None, "unit": "B/s",
                          "error": str(e)[-500:]}), flush=True)
        return 1


def run_converge_benchmark() -> int:
    """Convergence-matrix gate (`bench.py --converge`): train every
    runnable (wire format x reduction op x algorithm) cell of the
    horovod_tpu/converge matrix on the HOROVOD_CONVERGE_MODELS rows
    (default resnet18,gpt_tiny) and gate on the verdict — every
    runnable cell within its documented tolerance vs its baseline
    (docs/benchmarks.md tolerance table), every rejected-by-design
    cell failing fast with its structured error. Prints the verdict as
    ONE JSON line; exits nonzero unless ``ok``. This is the gate every
    wire-format or algorithm change runs before it ships (ROADMAP
    item 1)."""
    ndev = int(os.environ.get("HVD_BENCH_CONVERGE_DEVICES", "8"))
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") and ndev > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ndev}").strip()
    try:
        import horovod_tpu as hvd
        from horovod_tpu.converge import run_matrix

        hvd.init()
        verdict = run_matrix()
        # one compact line: drop per-step curves, keep the judgments
        summary = {"metric": "converge_matrix", "ok": verdict["ok"],
                   "world": verdict["world"],
                   "tol_scale": verdict["tol_scale"], "models": {}}
        for model, cells in verdict["models"].items():
            summary["models"][model] = {
                name: ({"status": "ran", "pass": e["pass"],
                        "final": round(e["final"], 4),
                        "final_rel": e["final_rel"],
                        "area_rel": e["area_rel"],
                        "baseline": e["baseline"]}
                       if e["status"] == "ran" else
                       {"status": e["status"],
                        "error_ok": e.get("error_ok")})
                for name, e in cells.items()}
        print(json.dumps(summary), flush=True)
        hvd.shutdown()
        return 0 if verdict["ok"] else 1
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        print(json.dumps({"metric": "converge_matrix", "ok": False,
                          "error": str(e)[-500:]}), flush=True)
        return 1


def run_ckpt_benchmark() -> int:
    """Loopback checkpoint benchmark (`bench.py --ckpt`): drive the
    sharded checkpoint plane (horovod_tpu/ckpt) over a synthetic
    parameter tree and print THREE JSON metric lines consistent with
    `--serve`/`--metrics` — ckpt_save_ms (synchronous save, submit ->
    durable commit), ckpt_blocking_ms (async save()'s step-loop stall:
    device sync + bounded handoff only) and ckpt_restore_ms (read ->
    full CRC-verified tree). The async/sync ratio is the tentpole's
    acceptance bar: blocking time <= 25% of the equivalent synchronous
    save."""
    import shutil
    import statistics
    import tempfile

    try:
        import jax
        import jax.numpy as jnp

        from horovod_tpu.ckpt import ShardedCheckpointer

        platform = jax.devices()[0].platform
        mb = int(os.environ.get("HVD_BENCH_CKPT_MB", "64"))
        iters = int(os.environ.get("HVD_BENCH_CKPT_ITERS", "4"))
        # a realistic tree shape: a few large matmul-ish leaves + many
        # small ones (biases/scales), device-resident so save() pays a
        # real device->host sync
        rows = max((mb * (1 << 20)) // (4 * 1024) // 8, 8)
        key = jax.random.PRNGKey(0)
        tree = {"params": {}}
        for i in range(8):
            tree["params"][f"w{i}"] = jax.device_put(
                jax.random.normal(jax.random.fold_in(key, i),
                                  (rows, 1024), jnp.float32))
        for i in range(32):
            tree["params"][f"b{i}"] = jnp.full((128,), float(i))
        tree["step"] = 0
        jax.block_until_ready(tree["params"]["w0"])
        root = tempfile.mkdtemp(prefix="hvd_ckpt_bench.")
        try:
            sync_ms, blocking_ms = [], []
            with ShardedCheckpointer(
                    os.path.join(root, "sync"), async_save=False,
                    max_to_keep=2) as ck:
                for i in range(iters):
                    t0 = time.perf_counter()
                    ck.save(i, tree, force=True)
                    sync_ms.append((time.perf_counter() - t0) * 1000.0)
                t0 = time.perf_counter()
                out = ck.restore()
                restore_ms = (time.perf_counter() - t0) * 1000.0
                assert out["params"]["w0"].shape == (rows, 1024)
            with ShardedCheckpointer(
                    os.path.join(root, "async"), async_save=True,
                    max_to_keep=2) as ck:
                ck.save(0, tree, force=True)      # warmup: thread spinup
                ck.wait_until_finished()
                for i in range(1, iters + 1):
                    t0 = time.perf_counter()
                    ck.save(i, tree, force=True)
                    blocking_ms.append(
                        (time.perf_counter() - t0) * 1000.0)
                    ck.wait_until_finished()   # isolate per-save stall
                ck.wait_until_finished()
        finally:
            shutil.rmtree(root, ignore_errors=True)
        save = statistics.median(sync_ms)
        blocking = statistics.median(blocking_ms)
        common = {"platform": platform, "tree_mb": mb, "iters": iters,
                  "blocking_over_sync": round(blocking / save, 4)}
        if os.environ.get("HVD_BENCH_METRICS") == "1":
            from horovod_tpu import obs
            print(json.dumps({"metric": "metrics_snapshot",
                              "value": obs.get_registry().snapshot()}),
                  flush=True)
        for metric, value in (("ckpt_save_ms", save),
                              ("ckpt_blocking_ms", blocking),
                              ("ckpt_restore_ms", restore_ms)):
            print(json.dumps({"metric": metric,
                              "value": round(value, 3), "unit": "ms",
                              **common}), flush=True)
        return 0
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric in ("ckpt_save_ms", "ckpt_blocking_ms",
                       "ckpt_restore_ms"):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": "ms", "error": str(e)[-500:]}),
                  flush=True)
        return 1


def _redist_bench_tree(rows, fill: bool):
    import numpy as np
    if fill:
        tree = {f"w{i}": np.arange(rows * 1024, dtype=np.float32)
                .reshape(rows, 1024) * (i + 1) for i in range(4)}
        tree["step"] = 7
    else:
        tree = {f"w{i}": np.zeros((rows, 1024), np.float32)
                for i in range(4)}
        tree["step"] = 0
    return tree


def _redist_bench_worker(rows, world):
    """One bench rank (real process via the multiprocessing runner —
    threads would serialize the numpy/socket work on one GIL and
    misreport the wire path by ~10x). Returns (ms, ok)."""
    import os

    import numpy as np

    from horovod_tpu.redist import RingTransport, Spec, redistribute

    r = int(os.environ["HOROVOD_RANK"])
    local = _redist_bench_tree(rows, fill=(r == 0))
    t = RingTransport.connect(r, world, prefix="bench.redist",
                              timeout=120)
    # align ranks before timing: process spawn + jax import skew would
    # otherwise be billed to the transfer (the first rank waits in the
    # rendezvous for the last one to start)
    t._ring.barrier()
    t0 = time.perf_counter()
    out = redistribute(local, Spec.full(world, holders=(0,)),
                       Spec.full(world), t, tag="bench")
    ms = (time.perf_counter() - t0) * 1000.0
    t.close()
    oracle = _redist_bench_tree(rows, fill=True)
    ok = all(np.array_equal(out[k], oracle[k]) for k in
             ("w0", "w1", "w2", "w3")) and out["step"] == 7
    return (ms, bool(ok))


def run_redist_benchmark() -> int:
    """Redistribution microbench (`bench.py --redist`): an in-memory
    N->M weight redistribution over the p2p ring (one holder fanning a
    synthetic tree out to W real worker processes, the elastic-grow
    shape) timed against the checkpoint save+restore round trip it
    replaces, at MATCHED tree sizes — plus a serve hot-swap latency
    (`weight_swap_ms`: publish -> poll -> swap_params on a tiny GPT
    executor). Emits one JSON line per metric consistent with
    --serve/--ckpt: redist_ms, redist_bytes_per_s, weight_swap_ms
    (each carrying ckpt_roundtrip_ms + in_memory_over_ckpt for the
    comparison)."""
    import shutil
    import statistics
    import tempfile
    import uuid

    try:
        import numpy as np

        from horovod_tpu.ckpt import ShardedCheckpointer
        from horovod_tpu.native.store import StoreServer
        from horovod_tpu.spark import MultiprocessingJobRunner
        from horovod_tpu.spark import run as spark_run

        mb = int(os.environ.get("HVD_BENCH_REDIST_MB", "32"))
        world = int(os.environ.get("HVD_BENCH_REDIST_WORLD", "4"))
        rows = max((mb * (1 << 20)) // (4 * 1024) // 4, 4)
        tree = _redist_bench_tree(rows, fill=True)
        tree_bytes = sum(v.nbytes for v in tree.values()
                         if isinstance(v, np.ndarray))

        srv = StoreServer()
        returns = spark_run(
            _redist_bench_worker, args=(rows, world), num_proc=world,
            job_runner=MultiprocessingJobRunner(),
            env={"HOROVOD_NATIVE_KV_ADDR": "127.0.0.1",
                 "HOROVOD_NATIVE_KV_PORT": str(srv.port),
                 "HOROVOD_JOB_ID": uuid.uuid4().hex[:8]})
        srv.close()
        assert all(ok for _, ok in returns), "bench tree mismatch"
        redist_ms = max(ms for ms, _ in returns)
        moved = tree_bytes * (world - 1)

        # the round trip it replaces: durable save + one full restore
        root = tempfile.mkdtemp(prefix="hvd_redist_bench.")
        try:
            with ShardedCheckpointer(root, rank=0, world=1,
                                     async_save=False) as ck:
                t0 = time.perf_counter()
                ck.save(0, tree, force=True)
                save_ms = (time.perf_counter() - t0) * 1000.0
                t0 = time.perf_counter()
                out = ck.restore(0, via="local")
                restore_ms = (time.perf_counter() - t0) * 1000.0
                assert np.array_equal(out["w0"], tree["w0"])
        finally:
            shutil.rmtree(root, ignore_errors=True)
        ckpt_roundtrip_ms = save_ms + restore_ms

        # serve hot-swap: publish -> poll -> swap on a live executor
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.gpt import GPT, GPTConfig
        from horovod_tpu.redist import WeightPublisher, WeightSubscriber
        from horovod_tpu.serve import ShardedExecutor

        srv = StoreServer()
        cfg = GPTConfig(vocab_size=256, num_layers=2, num_heads=4,
                        head_dim=16, max_seq_len=64, decode=True,
                        dtype=jnp.float32,
                        attention_impl="reference")
        model = GPT(cfg)
        toks = jnp.zeros((2, 8), jnp.int32)
        params = model.init(
            jax.random.PRNGKey(0), toks,
            positions=jnp.zeros((2,), jnp.int32),
            update_mask=jnp.zeros((2,), bool))["params"]
        ex = ShardedExecutor(model, params, max_batch=2, max_len=64)
        pub = WeightPublisher("bench", kv_addr="127.0.0.1",
                              kv_port=srv.port)
        sub = WeightSubscriber("bench", kv_addr="127.0.0.1",
                               kv_port=srv.port, template=params)
        swap_ms = []
        for i in range(5):
            nxt = jax.tree_util.tree_map(lambda x: x + 0.01, params)
            pub.publish(nxt)
            v, got = sub.poll()
            # time the SWAP span only — the same span the production
            # hvd_weight_swap_ms histogram covers (fetch/crc/assembly
            # is the stream-adoption cost, not the swap fence)
            t0 = time.perf_counter()
            assert ex.swap_params(got, version=v)
            swap_ms.append((time.perf_counter() - t0) * 1000.0)
        pub.close()
        sub.close()
        srv.close()

        common = {"world": world, "tree_mb": mb, "transport": "ring",
                  "ckpt_roundtrip_ms": round(ckpt_roundtrip_ms, 3),
                  "in_memory_over_ckpt": round(
                      redist_ms / ckpt_roundtrip_ms, 4)}
        if os.environ.get("HVD_BENCH_METRICS") == "1":
            from horovod_tpu import obs
            print(json.dumps({"metric": "metrics_snapshot",
                              "value": obs.get_registry().snapshot()}),
                  flush=True)
        for metric, value, unit in (
                ("redist_ms", round(redist_ms, 3), "ms"),
                ("redist_bytes_per_s",
                 round(moved / (redist_ms / 1000.0), 1), "B/s"),
                ("weight_swap_ms",
                 round(statistics.median(swap_ms), 3), "ms")):
            print(json.dumps({"metric": metric, "value": value,
                              "unit": unit, **common}), flush=True)
        return 0
    except Exception as e:  # noqa: BLE001 — structured error, no traceback
        for metric, unit in (("redist_ms", "ms"),
                             ("redist_bytes_per_s", "B/s"),
                             ("weight_swap_ms", "ms")):
            print(json.dumps({"metric": metric, "value": None,
                              "unit": unit, "error": str(e)[-500:]}),
                  flush=True)
        return 1


def main() -> int:
    stem = os.environ.get("HVD_BENCH_STEM", "conv7")
    model_name = os.environ.get("HVD_BENCH_MODEL", "resnet50")
    metric = f"{model_name}_synthetic_img_sec_per_chip"
    bad = None
    if stem not in ("conv7", "space_to_depth"):
        bad = f"unknown HVD_BENCH_STEM {stem!r}"
    elif model_name not in _BENCH_MODELS:
        bad = f"unknown HVD_BENCH_MODEL {model_name!r}"
    if bad:
        # deterministic config error: fail before the retry loop
        print(json.dumps({
            "metric": metric, "value": None,
            "unit": "img/sec/chip", "vs_baseline": None,
            "error": bad}), flush=True)
        return 1
    errors = []
    t_start = time.monotonic()
    for attempt in range(1, MAX_ATTEMPTS + 1):
        remaining = MAX_TOTAL_S - (time.monotonic() - t_start)
        if attempt > 1 and remaining < 60:
            errors.append(f"stopping before attempt {attempt}: "
                          f"total budget {MAX_TOTAL_S}s nearly spent")
            break
        budget = min(ATTEMPT_TIMEOUT_S, max(int(remaining), 60))
        try:
            out = subprocess.run(
                [sys.executable, "-u", __file__, "--worker"],
                capture_output=True, text=True, timeout=budget,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
            result_line = metrics_line = None
            for line in out.stdout.splitlines():
                if line.startswith(_MARK):
                    result_line = line[len(_MARK):]
                elif line.startswith(_MARK_METRICS):
                    metrics_line = line[len(_MARK_METRICS):]
            if result_line is not None:
                print(result_line, flush=True)
                if metrics_line is not None:
                    print(json.dumps({"metric": "metrics_snapshot",
                                      "value": json.loads(metrics_line)}),
                          flush=True)
                return 0
            tail = (out.stdout + out.stderr).strip().splitlines()[-6:]
            errors.append(f"attempt {attempt}: rc={out.returncode}: "
                          + " | ".join(tail))
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timed out after "
                          f"{budget}s (TPU tunnel hang?)")
        left = MAX_TOTAL_S - (time.monotonic() - t_start)
        if attempt < MAX_ATTEMPTS and left > 60:
            # backoff counts against the total budget too
            time.sleep(min(BACKOFF_S * attempt, max(left - 60, 0)))
    out = {
        "metric": metric,
        "value": None,
        "unit": "img/sec/chip",
        "vs_baseline": None,
        "error": "; ".join(errors)[-2000:],
    }
    cached = _last_hardware_capture(metric)
    if cached is not None:
        # NOT the live value (that stays null) — the most recent real-TPU
        # capture of this metric from benchmarks/*_results.jsonl, so a
        # tunnel outage at capture time still surfaces the evidence
        out["last_hardware_capture"] = cached
    print(json.dumps(out), flush=True)
    return 1


def _last_hardware_capture(metric: str):
    """Most recent non-null real-TPU record of `metric` from the on-disk
    capture logs (benchmarks/*_results.jsonl), or None. Prefers the
    default operating point (B=32, conv7 stem) over sweep/A-B legs so an
    outage surfaces the headline capture, not whichever experiment ran
    last."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    best = best_default = None
    # mtime order, oldest first, so the newest file's newest record wins
    # (lexical order would put round10 before round3)
    for path in sorted(glob.glob(os.path.join(here, "benchmarks",
                                              "*_results.jsonl")),
                       key=os.path.getmtime):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("metric") == metric and \
                            rec.get("value") is not None and \
                            rec.get("platform", "tpu") == "tpu":
                        row = {k: rec[k] for k in
                               ("metric", "value", "unit", "vs_baseline",
                                "batch", "stem", "timing") if k in rec}
                        row["source"] = os.path.basename(path)
                        best = row
                        if rec.get("batch", 32) == 32 and \
                                rec.get("stem", "conv7") == "conv7":
                            best_default = row
        except OSError:
            continue
    return best_default or best


if __name__ == "__main__":
    # --metrics: fold step-time p50/p99 into the summary JSON and emit
    # the end-of-run registry snapshot (docs/metrics.md)
    if "--metrics" in sys.argv:
        os.environ["HVD_BENCH_METRICS"] = "1"
    if "--worker" in sys.argv:
        run_benchmark()
    elif "--serve-soak" in sys.argv or \
            os.environ.get("HVD_BENCH_SERVE_SOAK") == "1":
        sys.exit(run_serve_soak_benchmark())
    elif "--serve-fleet" in sys.argv or \
            os.environ.get("HVD_BENCH_SERVE_FLEET") == "1":
        sys.exit(run_fleet_benchmark())
    elif "--serve-disagg" in sys.argv or \
            os.environ.get("HVD_BENCH_SERVE_DISAGG") == "1":
        sys.exit(run_disagg_benchmark())
    elif "--autoscale" in sys.argv or \
            os.environ.get("HVD_BENCH_AUTOSCALE") == "1":
        sys.exit(run_autoscale_benchmark())
    elif "--kernel-parity" in sys.argv or \
            os.environ.get("HVD_BENCH_KERNEL_PARITY") == "1":
        sys.exit(run_kernel_parity())
    elif "--kv-tier" in sys.argv or \
            os.environ.get("HVD_BENCH_KVTIER") == "1":
        sys.exit(run_kvtier_benchmark())
    elif "--serve" in sys.argv or \
            os.environ.get("HVD_BENCH_SERVE") == "1":
        sys.exit(run_serve_benchmark())
    elif "--ckpt" in sys.argv or \
            os.environ.get("HVD_BENCH_CKPT") == "1":
        sys.exit(run_ckpt_benchmark())
    elif "--collectives" in sys.argv or \
            os.environ.get("HVD_BENCH_COLLECTIVES") == "1":
        sys.exit(run_collectives_benchmark())
    elif "--converge" in sys.argv or \
            os.environ.get("HVD_BENCH_CONVERGE") == "1":
        sys.exit(run_converge_benchmark())
    elif "--redist" in sys.argv or \
            os.environ.get("HVD_BENCH_REDIST") == "1":
        sys.exit(run_redist_benchmark())
    else:
        sys.exit(main())
