#!/usr/bin/env python
"""TF2 eager custom training loop with DistributedGradientTape
(reference: examples/tensorflow2/tensorflow2_mnist.py). Launch:

    python -m horovod_tpu.runner.launch -np 2 python examples/tf2_custom_loop.py

or standalone single-process. Shows the reference recipe: init,
broadcast once, averaged tape gradients, SyncBatchNormalization, and
rank-0-only logging.
"""
import numpy as np
import tensorflow as tf

import horovod_tpu.interop.tf as hvd


def main() -> None:
    hvd.init()
    tf.random.set_seed(42 + hvd.rank())      # diverged init on purpose

    model = tf.keras.Sequential([
        tf.keras.layers.Input((16,)),
        tf.keras.layers.Dense(32),
        hvd.SyncBatchNormalization(axis=-1),  # stats span the GLOBAL batch
        tf.keras.layers.ReLU(),
        tf.keras.layers.Dense(2),
    ])
    opt = tf.keras.optimizers.SGD(0.05)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)

    rng = np.random.RandomState(hvd.rank())  # each rank its own shard
    x = rng.randn(256, 16).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int64)

    first = True
    for epoch in range(3):
        perm = rng.permutation(len(x))
        total, batches = 0.0, 0
        for s in range(0, len(x), 32):
            idx = perm[s:s + 32]
            with tf.GradientTape() as tape:
                loss = loss_fn(y[idx], model(x[idx], training=True))
            tape = hvd.DistributedGradientTape(tape)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            if first:
                # after the first step, not before: optimizer slots must
                # exist (the reference's broadcast timing rule)
                hvd.broadcast_variables(model.variables, root_rank=0)
                hvd.broadcast_variables(opt.variables, root_rank=0)
                first = False
            total += float(loss)
            batches += 1
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={total / batches:.4f}")

    # replicas converged identically (same synced start + averaged grads)
    flat = np.concatenate([v.numpy().ravel() for v in model.variables])
    gathered = hvd.allgather_object(flat)
    for other in gathered[1:]:
        np.testing.assert_allclose(gathered[0], other, rtol=1e-5,
                                   atol=1e-6)
    if hvd.rank() == 0:
        print(f"replicas identical across {hvd.size()} rank(s)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
