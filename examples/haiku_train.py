#!/usr/bin/env python
"""dm-haiku model trained through the haiku binding (reference analog:
per-framework examples, e.g. examples/pytorch/pytorch_mnist.py).

    HVD_EXAMPLE_CPU=8 python examples/haiku_train.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import haiku as hk                                          # noqa: E402
import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
import horovod_tpu.interop.haiku as hvd_hk                  # noqa: E402
from horovod_tpu.training import (init_replicated,          # noqa: E402
                                  shard_batch)


def main() -> None:
    hvd.init()
    mesh = hvd.core.basics.get_mesh()

    net = hk.transform(lambda x: hk.nets.MLP([64, 32, 4])(x))
    r = np.random.RandomState(0)
    x = r.randn(64, 16).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32) + 2 * (
        x[:, 0] > 0).astype(np.int32)

    rng = jax.random.PRNGKey(0)
    params = init_replicated(net.init(rng, jnp.asarray(x[:1])), mesh)

    def ce(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    step = hvd_hk.make_train_step(net, optax.adam(5e-3), mesh, loss_fn=ce)
    opt = init_replicated(step.init_opt_state(params), mesh)
    xi, yi = shard_batch(x, mesh), shard_batch(y, mesh)
    for s in range(8):
        params, opt, loss = step(params, opt, rng, xi, yi)
    print(f"haiku final loss={float(loss):.4f}")

    def acc(out, labels):
        return jnp.mean((jnp.argmax(out, -1) == labels)
                        .astype(jnp.float32))

    ev = hvd_hk.make_eval_step(net, mesh, metric_fn=acc)
    print(f"haiku accuracy={float(ev(params, rng, xi, yi)):.3f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
