"""fp16 gradient compression + tensor-fusion threshold sweep.

BASELINE.json config scenario 3 (reference: torch DistributedOptimizer with
Compression.fp16, examples/pytorch/pytorch_synthetic_benchmark.py
--fp16-allreduce, and the HOROVOD_FUSION_THRESHOLD knob the autotuner
sweeps): train the same data-parallel model with none vs fp16 (bf16 wire)
compression, then sweep the engine's fusion threshold over the async path
and report fused-tensor counts per setting.

Run: python examples/compression_fusion_sweep.py [--steps 3]
"""
import argparse
import os

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.training import (init_replicated, make_train_step,  # noqa: E402
                                  shard_batch)


def train_with(compression, steps, mesh, model, variables):
    import jax.numpy as jnp
    params = init_replicated(variables["params"], mesh)
    step = make_train_step(
        lambda v, x: model.apply(v, x), optax.sgd(0.05), mesh,
        compression=compression, donate=False)
    opt_state = init_replicated(step.init_opt_state(params), mesh)
    rng = np.random.RandomState(0)
    loss = None
    for _ in range(steps):
        xb = shard_batch(rng.rand(16, 8).astype(np.float32), mesh)
        yb = shard_batch(rng.randint(0, 4, (16,)).astype(np.int32), mesh)
        params, opt_state, _, loss = step(params, opt_state, {}, xb, yb)
    return float(loss)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    n = hvd.size()

    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))

    model = Net()
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1, 8), np.float32))

    # --- compression comparison (wire dtype none vs bf16) -----------------
    loss_none = train_with(hvd.Compression.none, args.steps, mesh, model,
                           variables)
    loss_fp16 = train_with(hvd.Compression.fp16, args.steps, mesh, model,
                           variables)
    print(f"loss none={loss_none:.4f} fp16-wire={loss_fp16:.4f} "
          f"(drift {abs(loss_none - loss_fp16):.4f})")

    # --- fusion threshold sweep on the async engine -----------------------
    eng = hvd.core.basics.get_engine()
    tensors = [np.ones((n, 256), np.float32) * i for i in range(8)]
    for mb in (0, 1, 64):
        eng.fusion_threshold = mb * 1024 * 1024
        before = eng.tensors_fused
        hs = [hvd.allreduce_async(t, hvd.Sum, name=f"sweep{mb}_{i}")
              for i, t in enumerate(tensors)]
        for h in hs:
            hvd.synchronize(h)
        print(f"fusion_threshold={mb}MB fused_tensors="
              f"{eng.tensors_fused - before}")
    print("sweep done")
    hvd.shutdown()


if __name__ == "__main__":
    main()
