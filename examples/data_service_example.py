#!/usr/bin/env python
"""Compute-service input pipeline (reference:
examples/tensorflow2/tensorflow2_mnist_data_service.py semantics): data
preprocessing runs in compute workers; the training process streams ready
batches.

    HVD_EXAMPLE_CPU=8 python examples/data_service_example.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import numpy as np                                          # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.data import (                              # noqa: E402
    ComputeClient, ComputeService, ComputeWorker,
)


def make_dataset(worker_idx, num_workers, n_samples=512, batch=32):
    """Each worker preprocesses its shard (simulated augmentation)."""
    def fn():
        rng = np.random.RandomState(worker_idx)
        shard = n_samples // num_workers
        for s in range(shard // batch):
            x = rng.rand(batch, 28, 28, 1).astype(np.float32)
            x = (x - x.mean()) / (x.std() + 1e-6)     # "preprocessing"
            y = rng.randint(0, 10, (batch,)).astype(np.int32)
            yield {"x": x, "y": y}
    return fn


def main() -> None:
    hvd.init()
    num_workers = 2

    # normally these run in a separate compute job (CPU hosts); in the
    # example they share the process
    svc = ComputeService(num_workers=num_workers)
    workers = [ComputeWorker(i, svc.config(),
                             make_dataset(i, num_workers))
               for i in range(num_workers)]
    svc.wait_for_workers()

    client = ComputeClient(svc.config())
    n_batches, n_images = 0, 0
    for batch in client.batches():
        n_batches += 1
        n_images += batch["x"].shape[0]
    print(f"trained on {n_batches} served batches / {n_images} images "
          f"from {num_workers} compute workers")

    client.close()
    for w in workers:
        w.shutdown()
    svc.shutdown()
    hvd.shutdown()


if __name__ == "__main__":
    main()
