#!/usr/bin/env python
"""PyTorch data-parallel training over the native shm plane (reference:
examples/pytorch/pytorch_mnist.py shape). Launch with hvdrun:

    python -m horovod_tpu.runner.launch -np 2 python examples/torch_cpu_ddp.py

or standalone single-process: python examples/torch_cpu_ddp.py
"""
import numpy as np
import torch

import horovod_tpu.interop.torch as hvd


def main() -> None:
    hvd.init()
    torch.manual_seed(1234 + hvd.rank())     # diverged init on purpose

    model = torch.nn.Sequential(
        torch.nn.Linear(16, 32), hvd.SyncBatchNorm(32), torch.nn.ReLU(),
        torch.nn.Linear(32, 2))  # BN statistics span the GLOBAL batch
    # rank 0's weights everywhere (examples convention: rank 0 is source)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())

    rng = np.random.RandomState(hvd.rank())  # each rank its own shard
    x = torch.from_numpy(rng.randn(256, 16).astype(np.float32))
    y = torch.from_numpy((x.numpy().sum(1) > 0).astype(np.int64))

    for epoch in range(3):
        perm = torch.randperm(len(x))
        total = 0.0
        for s in range(0, len(x), 32):
            idx = perm[s:s + 32]
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(model(x[idx]), y[idx])
            loss.backward()
            opt.step()                        # grads allreduced here
            total += float(loss)
        avg = hvd.allreduce(torch.tensor([total / (len(x) // 32)]))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: mean loss {float(avg):.4f} "
                  f"across {hvd.size()} rank(s)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
