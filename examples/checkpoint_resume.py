#!/usr/bin/env python
"""Checkpoint + resume: train, save via orbax, restart, continue
(reference convention: rank 0 saves; broadcast on resume —
examples/pytorch/pytorch_imagenet_resnet50.py).

    HVD_EXAMPLE_CPU=8 python examples/checkpoint_resume.py
"""
import os
import tempfile

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.models import ViT_Tiny                     # noqa: E402
from horovod_tpu.training import (init_replicated,          # noqa: E402
                                  make_train_step, shard_batch)


def build(mesh):
    model = ViT_Tiny(num_classes=10, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)))
    params = init_replicated(variables["params"], mesh)
    step = make_train_step(model.apply, optax.adam(1e-3), mesh)
    opt = init_replicated(step.init_opt_state(params), mesh)
    return step, params, opt


def main() -> None:
    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    ckpt_dir = os.environ.get("CKPT_DIR") or tempfile.mkdtemp()

    r = np.random.RandomState(0)
    xb = shard_batch(r.rand(16, 32, 32, 3).astype(np.float32), mesh)
    yb = shard_batch(r.randint(0, 10, (16,)).astype(np.int32), mesh)

    # phase 1: train 3 steps, checkpoint asynchronously
    step, params, opt = build(mesh)
    with hvd.Checkpointer(ckpt_dir) as ckpt:
        for s in range(3):
            params, opt, _, loss = step(params, opt, {}, xb, yb)
            ckpt.save(s, {"params": params, "opt": opt})
        loss_before = float(loss)
    print(f"phase 1 trained to step 3, loss={loss_before:.4f}")

    # phase 2: fresh process state, restore latest, continue
    step, params, opt = build(mesh)
    with hvd.Checkpointer(ckpt_dir) as ckpt:
        restored = ckpt.restore(target={"params": params, "opt": opt})
    params, opt = restored["params"], restored["opt"]
    params, opt, _, loss = step(params, opt, {}, xb, yb)
    print(f"resumed from step {hvd.checkpoint.latest_step(ckpt_dir)}, "
          f"loss={float(loss):.4f} (continues below {loss_before:.4f})")
    hvd.shutdown()


if __name__ == "__main__":
    main()
