#!/usr/bin/env python
"""Llama-family pretraining (RMSNorm / RoPE / SwiGLU / GQA) on a hybrid
dp x sp x tp mesh — the modern open-weight LM architecture on the same
parallelism stack as examples/gpt_hybrid_parallel.py.

    HVD_EXAMPLE_CPU=8 python examples/llama_pretrain.py --dp 2 --sp 2 --tp 2
"""
import argparse
import time

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

from horovod_tpu.models.llama import (                      # noqa: E402
    Llama, LlamaConfig, llama_partition_rules,
)
from horovod_tpu.parallel.mesh_utils import make_mesh       # noqa: E402
from horovod_tpu.parallel.tp import shard_params            # noqa: E402
from horovod_tpu.training import make_gspmd_train_step      # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--fsdp", action="store_true",
                    help="shard params + optimizer state over dp "
                         "(ZeRO/FSDP, parallel/fsdp.py)")
    ap.add_argument("--remat", action="store_true",
                    help="per-block activation checkpointing")
    ap.add_argument("--attention", default=None,
                    choices=["dense", "ring", "ulysses", "zigzag"],
                    help="attention mode (default: ring when --sp > 1; "
                         "zigzag = causally load-balanced ring)")
    args = ap.parse_args()
    if args.attention in ("ring", "ulysses", "zigzag") and args.sp <= 1:
        ap.error(f"--attention {args.attention} requires --sp > 1")

    mesh = make_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
    cfg = LlamaConfig(
        vocab_size=256, num_layers=2, num_heads=4,
        num_kv_heads=args.kv_heads, head_dim=16,
        max_seq_len=args.seq, mesh=mesh,
        attention=args.attention or
        ("ring" if args.sp > 1 else "dense"),
        dtype=jnp.float32, remat=args.remat)
    model = Llama(cfg)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (2 * args.dp, args.seq)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    rules = llama_partition_rules()
    if args.fsdp:
        from horovod_tpu.parallel.fsdp import FSDPRules
        rules = FSDPRules(rules, mesh, min_size=2 ** 10)
    params = shard_params(params, mesh, rules)
    tx = optax.adamw(3e-3)
    opt = tx.init(params)
    step = make_gspmd_train_step(model.apply, tx, mesh, rules)

    print(f"llama {n_params/1e6:.1f}M params, mesh "
          f"dp={args.dp} sp={args.sp} tp={args.tp}"
          f"{' +fsdp' if args.fsdp else ''}, "
          f"gqa {cfg.num_heads}q/{cfg.num_kv_heads}kv")
    for i in range(args.steps):
        t0 = time.perf_counter()
        params, opt, loss = step(params, opt, tokens, targets)
        loss = float(loss)
        print(f"step {i}: loss {loss:.4f} "
              f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")


if __name__ == "__main__":
    main()
