"""tf.keras data-parallel training over the multi-process plane.

TPU-rebuild analog of the reference's keras example
(examples/keras/keras_mnist.py + tensorflow2/tensorflow2_keras_mnist.py):
the 3-step porting recipe — init, DistributedOptimizer, broadcast callback —
on a synthetic dataset (no downloads).

Run:  hvdrun -np 2 python examples/keras_train.py
"""
import numpy as np

import horovod_tpu.interop.keras as hvd


def main():
    import keras

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # synthetic 10-class problem; same dataset everywhere, sharded by rank
    rng = np.random.RandomState(0)
    x = rng.rand(1024, 32).astype(np.float32)
    w_true = rng.rand(32, 10).astype(np.float32)
    y = (x @ w_true).argmax(axis=1)
    xs, ys = x[rank::size], y[rank::size]

    keras.utils.set_random_seed(42 + rank)        # diverged init on purpose
    model = keras.Sequential([
        keras.layers.Input((32,)),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10),
    ])

    # scale LR by size (reference recipe), wrap the optimizer, broadcast
    opt = hvd.DistributedOptimizer(keras.optimizers.Adam(1e-3 * size))
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
        jit_compile=False,        # py_function collectives can't XLA-jit
    )

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(initial_lr=1e-3 * size,
                                                 warmup_epochs=2),
    ]
    hist = model.fit(xs, ys, epochs=4, batch_size=32,
                     verbose=2 if rank == 0 else 0, callbacks=callbacks)

    if rank == 0:
        print("final averaged accuracy:",
              round(hist.history["accuracy"][-1], 3))
    hvd.shutdown()


if __name__ == "__main__":
    main()
