#!/usr/bin/env python
"""ViT fine-tune, data-parallel over the mesh (reference analog:
examples/pytorch/pytorch_imagenet_resnet50.py shape, modern encoder).

    HVD_EXAMPLE_CPU=8 python examples/vit_train.py --epochs 1
"""
import argparse

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.models import ViT_Tiny                     # noqa: E402
from horovod_tpu.training import (init_replicated,          # noqa: E402
                                  make_train_step, shard_batch)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()

    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    platform = jax.devices()[0].platform
    model = ViT_Tiny(num_classes=10,
                     dtype=jnp.float32 if platform == "cpu"
                     else jnp.bfloat16)

    r = np.random.RandomState(0)
    n = 4 * args.batch_size
    images = r.rand(n, 32, 32, 3).astype(np.float32)
    labels = r.randint(0, 10, (n,)).astype(np.int32)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.asarray(images[:1]))
    params = init_replicated(variables["params"], mesh)
    step = make_train_step(model.apply, optax.adam(1e-3), mesh)
    opt = init_replicated(step.init_opt_state(params), mesh)

    steps = n // args.batch_size
    for epoch in range(args.epochs):
        total = 0.0
        for s in range(steps):
            lo, hi = s * args.batch_size, (s + 1) * args.batch_size
            xb = shard_batch(images[lo:hi], mesh)
            yb = shard_batch(labels[lo:hi], mesh)
            params, opt, _, loss = step(params, opt, {}, xb, yb)
            total += float(loss)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={total / steps:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
