#!/usr/bin/env python
"""Spark LightningEstimator (reference: horovod.spark.lightning
TorchEstimator): fit a LightningModule-protocol model on array data with
Store-backed materialization and checkpoints. Works with a plain torch
module implementing the protocol — pytorch_lightning itself is optional.

    python examples/lightning_estimator.py
Under a launcher the training is data-parallel over the CPU plane:
    hvdrun -np 2 python examples/lightning_estimator.py
"""
import tempfile

import numpy as np
import torch

from horovod_tpu.spark import LightningEstimator, LocalStore


class LitRegressor(torch.nn.Module):
    """Duck-typed LightningModule: configure_optimizers + training_step
    (+ optional validation_step / epoch hooks)."""

    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(8, 32), torch.nn.ReLU(), torch.nn.Linear(32, 1))

    def forward(self, x):
        return self.net(x)

    def configure_optimizers(self):
        opt = torch.optim.Adam(self.parameters(), lr=1e-2)
        return {"optimizer": opt,
                "lr_scheduler": {
                    "scheduler": torch.optim.lr_scheduler.StepLR(
                        opt, step_size=2, gamma=0.5),
                    "interval": "epoch"}}

    def training_step(self, batch, batch_idx):
        x, y = batch
        return torch.nn.functional.mse_loss(self.net(x), y)

    def validation_step(self, batch, batch_idx):
        x, y = batch
        return torch.nn.functional.mse_loss(self.net(x), y)


def main() -> None:
    import os
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    rng = np.random.RandomState(0)
    x = rng.rand(512, 8).astype(np.float32)
    w = rng.rand(8, 1).astype(np.float32)
    y = (x @ w + 0.01 * rng.randn(512, 1)).astype(np.float32)

    with tempfile.TemporaryDirectory() as d:
        store = LocalStore(d)
        est = LightningEstimator(LitRegressor(), epochs=5, batch_size=64,
                                 store=store, run_id="lit",
                                 validation=0.2)
        model = est.fit(x, y)
        preds = model.predict(x[:4])
        if rank == 0:
            print(f"lightning history: "
                  f"{[round(h['loss'], 4) for h in est.history]}")
            print(f"lightning val_loss: {est.history[-1]['val_loss']:.4f}")
            print(f"lightning preds shape: {preds.shape}")


if __name__ == "__main__":
    main()
