#!/usr/bin/env python
"""Synthetic data-parallel benchmark (reference:
examples/pytorch/pytorch_synthetic_benchmark.py): random batches through
a zoo model — ResNet-18/50/101 (full SyncBN train step), VGG-16 or
Inception V3 (train step with frozen norm/dropout stats; see
models/bench_zoo.py) — with the DistributedOptimizer; prints img/sec per
iteration and the aggregate.

    HVD_EXAMPLE_CPU=8 python examples/synthetic_benchmark.py --model resnet18
"""
import argparse
import time

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.models.bench_zoo import (                  # noqa: E402
    BENCH_MODELS, build_benchmark_model, default_image_size,
)
from horovod_tpu.training import (                          # noqa: E402
    init_replicated, make_train_step, shard_batch,
)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=list(BENCH_MODELS))
    p.add_argument("--batch-size", type=int, default=None,
                   help="per-device batch size")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--num-warmup", type=int, default=2)
    args = p.parse_args()

    hvd.init()
    mesh = hvd.core.basics.get_mesh()
    n = hvd.size()
    on_tpu = jax.devices()[0].platform == "tpu"
    # B=32: the on-hardware sweep recorded in docs/benchmarks.md
    # found 64 the worst measured point (bench.py uses the same)
    per_dev = args.batch_size or (32 if on_tpu else 2)
    hw = args.image_size or default_image_size(args.model, on_tpu)
    batch = per_dev * n

    # shared with bench.py: resnets run the full SyncBN train step;
    # vgg/inception time it with frozen norm/dropout stats (see
    # models/bench_zoo.py)
    apply_fn, params, batch_stats, has_bn = build_benchmark_model(
        args.model, hw)
    params = init_replicated(params, mesh)
    batch_stats = init_replicated(batch_stats, mesh)
    step = make_train_step(apply_fn, optax.sgd(0.01, momentum=0.9), mesh,
                           has_batch_stats=has_bn)
    opt_state = init_replicated(step.init_opt_state(params), mesh)

    rng = np.random.RandomState(0)
    images = shard_batch(rng.rand(batch, hw, hw, 3).astype(np.float32), mesh)
    labels = shard_batch(rng.randint(0, 1000, (batch,)).astype(np.int32),
                         mesh)

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {batch} ({per_dev}/device x {n})")

    for _ in range(args.num_warmup):
        params, opt_state, batch_stats, loss = step(
            params, opt_state, batch_stats, images, labels)
    jax.block_until_ready(loss)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        params, opt_state, batch_stats, loss = step(
            params, opt_state, batch_stats, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        img_secs.append(batch / dt)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {img_secs[-1]:.1f} img/sec total")
    if hvd.rank() == 0:
        print(f"Img/sec per device: {np.mean(img_secs) / n:.1f} "
              f"+-{1.96 * np.std(img_secs) / n:.1f}")
        print(f"Total img/sec on {n} device(s): {np.mean(img_secs):.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
