"""Shared example bootstrap: optional virtual CPU mesh via HVD_EXAMPLE_CPU."""
import os


def maybe_cpu_mesh() -> None:
    n = os.environ.get("HVD_EXAMPLE_CPU")
    if n:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
