#!/usr/bin/env python
"""Distributed run through the Ray executor (reference:
examples/ray/ray_train.py shape — RayExecutor.start/run/shutdown).

With Ray installed, workers are real actors (one process each) and run
plane collectives like any hvdrun job. Without Ray, the in-process
local backend demonstrates the same executor surface (start, run,
execute_single, run_remote/wait) — in-process workers share one
interpreter, so the fallback keeps the worker fn collective-free.

    python examples/ray_executor.py            # local backend fallback
    python examples/ray_executor.py --ray      # require real Ray
"""
import argparse
import os

import numpy as np


def train_fn(steps: int = 3) -> str:
    """Runs on every worker. Under real Ray each worker is a process
    with its identity env pushed by the coordinator, so the plane forms
    a job exactly as under hvdrun."""
    distinct_process = "RAY_WORKER" in os.environ
    if distinct_process and int(os.environ.get("HOROVOD_SIZE", "1")) > 1:
        from horovod_tpu.interop import _plane
        _plane.init()
        r, n = _plane.rank(), _plane.size()
        w = np.zeros(4, np.float32)
        rng = np.random.RandomState(r)
        for _ in range(steps):
            grad = _plane.allreduce_np(rng.rand(4).astype(np.float32)) / n
            w -= 0.1 * grad
        _plane.shutdown()
        return f"rank{r}/{n} w_sum={w.sum():.4f}"
    # local in-process backend: identity comes from the worker object
    return f"local worker on {os.uname().nodename}"


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--ray", action="store_true",
                   help="require a real Ray backend (no local fallback)")
    args = p.parse_args()

    from horovod_tpu.ray import RayExecutor
    backend = None
    if not args.ray:
        try:
            import ray  # noqa: F401
        except ImportError:
            from horovod_tpu.ray.runner import _LocalBackend
            backend = _LocalBackend()
    ex = RayExecutor(num_workers=args.workers, backend=backend,
                     env_vars={"RAY_WORKER": "1"} if backend is None
                     else None)
    ex.start()
    try:
        results = ex.run(train_fn)
        single = ex.execute_single(lambda: "driver-side probe ok")
        refs = ex.run_remote(lambda: os.getpid())
        pids = ex.wait(refs)
    finally:
        ex.shutdown()
    kind = "ray" if backend is None else "local"
    print(f"ray executor ({kind} backend): {len(results)} workers")
    for r in results:
        print(" ", r)
    print(f"  {single}; worker pids={sorted(set(pids))}")


if __name__ == "__main__":
    main()
