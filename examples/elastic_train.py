#!/usr/bin/env python
"""Elastic training (reference:
examples/elastic/pytorch/pytorch_mnist_elastic.py semantics): wrap the
training loop with @hvd.elastic.run, keep progress in a State, commit
every N batches; on reset the state rolls back to the last commit and
training resumes.

    HVD_EXAMPLE_CPU=8 python examples/elastic_train.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402


def main() -> None:
    hvd.init()
    n = hvd.size()

    w0 = jnp.zeros((4,))
    params = {"w": jnp.broadcast_to(w0[None], (n, 4))}
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    state = hvd.elastic.TrainState(
        params=params, opt_state=opt.init(params), epoch=0, batch=0)

    data = np.random.RandomState(0).randn(64, n, 4).astype(np.float32)

    @hvd.elastic.run
    def train(state):
        opt_state, params = state.opt_state, state.params
        for epoch in range(state.epoch, 3):
            for b in range(state.batch, len(data)):
                grads = {"w": jnp.asarray(data[b])}
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                if b % 16 == 0:
                    state.params, state.opt_state = params, opt_state
                    state.epoch, state.batch = epoch, b
                    state.commit()        # checkpoint + sync point
            state.batch = 0
            if hvd.rank() == 0:
                print(f"epoch {epoch} done; w[0]={float(params['w'][0,0]):.3f}")
        state.params, state.opt_state = params, opt_state

    train(state)
    hvd.shutdown()


if __name__ == "__main__":
    main()
