#!/usr/bin/env python
"""GPT pretraining on a hybrid dp x sp x tp mesh: Megatron-style tensor
parallelism + ring-attention sequence parallelism + data parallelism,
all expressed as shardings over one jax Mesh (capability beyond the
reference, built from the same collective primitives — see
horovod_tpu/parallel/).

    HVD_EXAMPLE_CPU=8 python examples/gpt_hybrid_parallel.py --dp 2 --sp 2 --tp 2
"""
import argparse
import time

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

from horovod_tpu.models.gpt import GPT, GPTConfig           # noqa: E402
from horovod_tpu.parallel.mesh_utils import make_mesh       # noqa: E402
from horovod_tpu.parallel.tp import (                       # noqa: E402
    gpt_partition_rules, shard_params,
)
from horovod_tpu.training import make_gspmd_train_step      # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--batch", type=int, default=2,
                   help="sequences per dp group")
    p.add_argument("--steps", type=int, default=3)
    args = p.parse_args()

    mesh = make_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
    cfg = GPTConfig(vocab_size=args.vocab, num_layers=args.layers,
                    num_heads=args.heads, head_dim=args.head_dim,
                    max_seq_len=args.seq_len,
                    attention="ring" if args.sp > 1 else "dense",
                    mesh=mesh, dtype=jnp.float32)
    model = GPT(cfg)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab,
                         (args.batch * args.dp, args.seq_len)).astype(
                             np.int32)
    targets = np.roll(tokens, -1, axis=1)

    params = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens))["params"]
    rules = gpt_partition_rules()
    params = shard_params(params, mesh, rules)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    step = make_gspmd_train_step(model.apply, tx, mesh, rules)

    n_params = sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(params))
    print(f"mesh dp={args.dp} sp={args.sp} tp={args.tp}; "
          f"{n_params / 1e6:.2f}M params; "
          f"attention={'ring' if args.sp > 1 else 'dense'}")

    for s in range(args.steps):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(tokens),
                                       jnp.asarray(targets))
        jax.block_until_ready(loss)
        print(f"step {s}: loss={float(loss):.4f} "
              f"({time.perf_counter() - t0:.2f}s)")


if __name__ == "__main__":
    main()
