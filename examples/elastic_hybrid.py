#!/usr/bin/env python
"""Elastic training under HYBRID parallelism (tp > 1).

The reference's elastic mode is data-parallel only; this framework
extends it with defined semantics for model-parallel meshes
(docs/elastic.md): the tp/sp/pp/ep factorization is declared once with
`ElasticMeshSpec` and stays fixed — `dp` absorbs every resize, and a
world that no longer fits fails fast with `MeshResizeError` instead of
training a silently different layout. `GSPMDState` keeps committed
state as host trees and re-places it on each incarnation's mesh with
the same partition rules (reshard-on-restore).

    HVD_EXAMPLE_CPU=8 python examples/elastic_hybrid.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402
from jax.sharding import PartitionSpec as P                 # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.elastic import (ElasticMeshSpec, GSPMDState,  # noqa: E402
                                 MeshResizeError)
from horovod_tpu.parallel.tp import PartitionRules          # noqa: E402
from horovod_tpu.training import make_gspmd_train_step      # noqa: E402


def main() -> None:
    hvd.init()

    # fixed model parallelism: tp=2; dp = devices / 2 on every
    # incarnation (8 devices -> dp=4)
    spec = ElasticMeshSpec(tp=2)
    rules = PartitionRules([(r"w", P(None, "tp"))])
    rs = np.random.RandomState(0)
    state = GSPMDState(
        spec, rules,
        params={"w": (rs.randn(16, 32) * 0.1).astype(np.float32)},
        step=0)

    @hvd.elastic.run
    def train(state):
        mesh = state.mesh                 # this incarnation's mesh
        print(f"mesh dp={dict(mesh.shape).get('dp', 1)} "
              f"tp={dict(mesh.shape)['tp']}", flush=True)
        tx = optax.sgd(0.05)
        step = make_gspmd_train_step(
            lambda v, x: jnp.tanh(x @ v["params"]["w"]), tx, mesh, rules,
            batch_spec=P("dp", None),
            loss_fn=lambda y, t: ((y - t) ** 2).mean())
        params = state.placed("params")   # reshard-on-restore
        opt = tx.init(params)
        while state.step < 6:
            rng = np.random.RandomState(state.step)
            x = jnp.asarray(rng.rand(8, 16).astype(np.float32))
            y = jnp.asarray(rng.rand(8, 32).astype(np.float32))
            params, opt, loss = step(params, opt, x, y)
            state.step += 1
            if state.step % 3 == 0:
                state.update_from_device(params=params)
                state.commit()
                print(f"step {state.step} committed "
                      f"loss={float(loss):.5f}", flush=True)
        return params

    train(state)

    # the fail-fast contract: a world that does not fit the fixed
    # factorization raises a clear MeshResizeError
    try:
        ElasticMeshSpec(tp=2).build(jax.devices()[:3])
    except MeshResizeError as e:
        print(f"misfit world rejected: {e}", flush=True)

    print("elastic hybrid done", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
