#!/usr/bin/env python
"""Adasum reduction (reference: examples/adasum/adasum_bench.ipynb,
docs/adasum_user_guide): scale-invariant gradient combination — compare
hvd.Adasum against plain averaging on gradients of very different
magnitudes.

    HVD_EXAMPLE_CPU=8 python examples/adasum_example.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import numpy as np                                          # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402


def main() -> None:
    hvd.init()
    n = hvd.size()
    rng = np.random.RandomState(0)

    # ranks produce gradients at wildly different scales
    scales = np.logspace(0, 3, n).astype(np.float32)
    grads = rng.randn(n, 512).astype(np.float32) * scales[:, None]

    avg = np.asarray(hvd.allreduce(grads, hvd.Average))[0]
    ada = np.asarray(hvd.allreduce(grads, hvd.Adasum))[0]

    if hvd.rank() == 0:
        print(f"input norms per rank: "
              f"{[f'{np.linalg.norm(g):.1f}' for g in grads]}")
        print(f"Average result norm: {np.linalg.norm(avg):.2f} "
              f"(dominated by the largest rank)")
        print(f"Adasum  result norm: {np.linalg.norm(ada):.2f} "
              f"(scale-adaptive combination)")

    # Two-level variant (AdasumGpuAllreduceOp analog): sum within each
    # "host" group, Adasum across groups — here on a simulated 2x(n/2)
    # topology via the local_size override.
    from horovod_tpu.ops.adasum import adasum_allreduce
    if n % 2 == 0 and n >= 4:
        hier = np.asarray(adasum_allreduce(grads, hierarchical=True,
                                           local_size=n // 2))[0]
        if hvd.rank() == 0:
            print(f"Hierarchical Adasum (2 groups x {n // 2}) norm: "
                  f"{np.linalg.norm(hier):.2f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
