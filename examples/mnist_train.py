#!/usr/bin/env python
"""MNIST-style classifier training (reference:
examples/tensorflow2/tensorflow2_keras_mnist.py semantics): Flax CNN,
DistributedOptimizer, rank-0 state broadcast, LR warmup + metric-average
callbacks. Uses synthetic digits unless --data points at an npz with
x/y arrays (no dataset download in the example itself).

    HVD_EXAMPLE_CPU=8 python examples/mnist_train.py --epochs 2
"""
import argparse

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import flax.linen as nn                                     # noqa: E402
import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.callbacks import (                         # noqa: E402
    LearningRate, LearningRateWarmupCallback, MetricAverageCallback,
)
from horovod_tpu.data import shard_indices                  # noqa: E402
from horovod_tpu.training import cross_entropy_loss         # noqa: E402


class CNN(nn.Module):
    """Small MNIST CNN (kept light so the CPU-mesh demo runs quickly;
    scale channels up freely on TPU)."""
    features: int = 8

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(self.features * 2, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def load_data(path):
    if path:
        with np.load(path) as d:
            return d["x"].astype(np.float32), d["y"].astype(np.int32)
    rng = np.random.RandomState(0)
    x = rng.rand(512, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, (512,)).astype(np.int32)
    # make the synthetic task learnable: brightness encodes the label
    x += y[:, None, None, None] / 10.0
    return x, y


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-device batch size")
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--data", default=None, help="npz with x/y arrays")
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    x, y = load_data(args.data)

    model = CNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))[
        "params"]
    lr = LearningRate(args.lr)
    opt = hvd.DistributedOptimizer(optax.adam(args.lr))

    # replicate: stacked params, one row per device (SPMD data parallel)
    params = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), params)
    opt_state = opt.init(params)

    @jax.jit
    def forward_backward(params, xb, yb):
        def loss_one(p, xr, yr):
            return cross_entropy_loss(model.apply({"params": p}, xr), yr)

        def total(ps):
            return jax.vmap(loss_one)(ps, xb, yb).mean()
        return jax.value_and_grad(total)(params)

    warmup = LearningRateWarmupCallback(lr, warmup_epochs=1, verbose=False)
    metric_avg = MetricAverageCallback()
    global_bs = args.batch_size * n
    steps = len(x) // global_bs
    for epoch in range(args.epochs):
        order = np.random.RandomState(epoch).permutation(len(x))
        total_loss = 0.0
        for s in range(steps):
            warmup.on_batch_begin(s, epoch)
            idx = order[s * global_bs:(s + 1) * global_bs]
            xb = jnp.asarray(x[idx]).reshape(n, args.batch_size, 28, 28, 1)
            yb = jnp.asarray(y[idx]).reshape(n, args.batch_size)
            loss, grads = forward_backward(params, xb, yb)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            total_loss += float(loss)
        logs = {"loss": total_loss / steps}
        metric_avg.on_epoch_end(epoch, logs)
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={logs['loss']:.4f} "
                  f"lr={float(lr):.2e}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
