#!/usr/bin/env python
"""Process sets: collectives over device subgroups (reference:
docs/process_set.rst usage; process_sets.py API).

    HVD_EXAMPLE_CPU=8 python examples/process_sets_example.py
"""
from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import numpy as np                                          # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402


def main() -> None:
    hvd.init()
    n = hvd.size()
    assert n >= 4, "needs >= 4 devices (set HVD_EXAMPLE_CPU=8)"

    even = hvd.add_process_set(list(range(0, n, 2)))
    odd = hvd.add_process_set(list(range(1, n, 2)))

    x = np.arange(n, dtype=np.float32)[:, None] + 1   # rank i -> i+1

    full = np.asarray(hvd.allreduce(x, hvd.Sum))[0, 0]
    ev = np.asarray(hvd.allreduce(x[0::2], hvd.Sum, process_set=even))[0, 0]
    od = np.asarray(hvd.allreduce(x[1::2], hvd.Sum, process_set=odd))[0, 0]

    print(f"global sum over {n} ranks: {full}")
    print(f"even-set sum {even.ranks}: {ev}")
    print(f"odd-set sum  {odd.ranks}: {od}")

    hvd.remove_process_set(even)
    hvd.remove_process_set(odd)
    hvd.shutdown()


if __name__ == "__main__":
    main()
