#!/usr/bin/env python
"""Pipeline parallelism: a stage-partitioned MLP trained with GPipe
microbatching over a 'pp' mesh axis (parallel/pp.py — capability beyond
the reference, whose SURVEY §2.6 accounting lists PP as absent).

Each device owns one stage's parameters; activations advance
stage-to-stage with lax.ppermute inside the scan over clock ticks, and
the backward pass flows through the same SPMD program via jax autodiff
(--schedule gpipe) or the explicit 1F1B schedule (--schedule 1f1b),
which bounds live activations at 2S-1 per stage instead of M and
accumulates parameter grads online.

    HVD_EXAMPLE_CPU=8 python examples/pp_pipeline.py --stages 4
    HVD_EXAMPLE_CPU=8 python examples/pp_pipeline.py --schedule 1f1b
"""
import argparse
import time

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
from jax.sharding import PartitionSpec as P                 # noqa: E402

from horovod_tpu.parallel.mesh_utils import make_mesh       # noqa: E402
from horovod_tpu.parallel.pp import (gpipe_and_return,      # noqa: E402
                                     pipeline_1f1b)


def run_gpt(args, S, M, mb) -> None:
    """Pipeline the real GPT decoder with 1F1B (+interleaved virtual
    stages): one SPMD program, stage hops on neighbor ppermutes."""
    import optax

    from horovod_tpu.models.gpt import GPTConfig
    from horovod_tpu.models.gpt_pp import gpt_pp_init, make_gpt_pp_step

    V = args.virtual
    cfg = GPTConfig(vocab_size=128, num_layers=S * V, num_heads=4,
                    head_dim=8, max_seq_len=32, dtype=jnp.float32)
    mesh = make_mesh(pp=S, devices=jax.devices()[:S])
    params = gpt_pp_init(cfg, S, jax.random.PRNGKey(0), virtual=V)
    step = make_gpt_pp_step(cfg, mesh, num_microbatches=M, virtual=V)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 128, (M * mb, 32)).astype(np.int32)
    tgts = np.roll(toks, -1, axis=1)
    sched = f"1F1B x {V} virtual" if V > 1 else "1F1B"
    print(f"GPT-PP: {S} stages ({sched}), {M} microbatches")
    for i in range(args.steps):
        t0 = time.perf_counter()
        loss, grads = step(params, toks, tgts)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        print(f"step {i}: loss {float(loss):.4f} "
              f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")
    print("gpt pipeline done")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--mb-size", type=int, default=8)
    ap.add_argument("--width", type=int, default=32)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--schedule", choices=["gpipe", "1f1b"],
                    default="gpipe")
    ap.add_argument("--model", choices=["mlp", "gpt"], default="mlp",
                    help="gpt pipelines the real decoder "
                         "(models/gpt_pp.py: embed outside, blocks "
                         "staged, head in the loss)")
    ap.add_argument("--virtual", type=int, default=1,
                    help="virtual stages per device for --model gpt "
                         "(interleaved schedule)")
    args = ap.parse_args()

    S, M, mb, D = args.stages, args.microbatches, args.mb_size, args.width
    n_dev = len(jax.devices())
    if n_dev % S:
        raise SystemExit(f"--stages {S} must divide device count {n_dev}")
    if args.model == "gpt":
        return run_gpt(args, S, M, mb)
    # leftover devices become a (here unused) dp axis so the mesh covers
    # every device; the pipeline specs replicate over it
    mesh = make_mesh(dp=n_dev // S, pp=S)
    rng = np.random.RandomState(0)
    # one [D, D] weight per stage, stacked on the pp-sharded leading axis
    Ws = jnp.asarray(rng.randn(S, D, D) * (1.0 / np.sqrt(D)), jnp.float32)
    xs = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
    # regression target produced by a fixed random deep net
    tgt = jnp.asarray(np.tanh(rng.randn(M, mb, D)), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    if args.schedule == "gpipe":
        def loss_fn(w_local, xs, tgt):
            out = gpipe_and_return(stage_fn, w_local[0], xs, "pp")
            return ((out - tgt) ** 2).mean()

        grad_fn = jax.jit(jax.shard_map(
            jax.value_and_grad(loss_fn), mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P(), P("pp"))))
        print(f"GPipe: {S} stages x {M} microbatches "
              f"({S + M - 1} ticks/step)")
    else:
        def step_1f1b(w_local, xs, tgt):
            loss, g = pipeline_1f1b(
                stage_fn, w_local[0], xs, tgt,
                lambda y, t: ((y - t) ** 2).mean(), "pp")
            return loss, g[None]          # restore the stage axis

        grad_fn = jax.jit(jax.shard_map(
            step_1f1b, mesh=mesh,
            in_specs=(P("pp"), P(), P()), out_specs=(P(), P("pp"))))
        print(f"GPipe: {S} stages x {M} microbatches — 1F1B schedule "
              f"({M + 2 * S - 1} ticks/step, <=2S-1 live activations)")

    lr = 0.2
    for step in range(args.steps):
        t0 = time.perf_counter()
        loss, grads = grad_fn(Ws, xs, tgt)
        Ws = Ws - lr * grads
        print(f"step {step}: loss {float(loss):.5f} "
              f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")


if __name__ == "__main__":
    main()
