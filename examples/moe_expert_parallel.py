#!/usr/bin/env python
"""MoE-GPT over a dp x ep mesh: experts sharded over 'ep', tokens
dispatched via all_to_all, Switch aux loss in the objective.

    HVD_EXAMPLE_CPU=8 python examples/moe_expert_parallel.py --steps 2
"""
import argparse

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
import numpy as np                                          # noqa: E402
import optax                                                # noqa: E402
from jax.sharding import PartitionSpec as P                 # noqa: E402

import horovod_tpu as hvd                                   # noqa: E402
from horovod_tpu.models import (MoEGPT, MoEGPTConfig,       # noqa: E402
                                moe_aux_loss, moe_partition_rules)
from horovod_tpu.parallel.mesh_utils import make_mesh       # noqa: E402
from horovod_tpu.parallel.tp import shard_params            # noqa: E402
from horovod_tpu.training import make_gspmd_train_step      # noqa: E402


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--ep", type=int, default=4)
    p.add_argument("--experts", type=int, default=4)
    p.add_argument("--top-k", type=int, default=1,
                   help="1 = Switch routing, 2 = GShard/Mixtral top-2")
    args = p.parse_args()

    hvd.init()
    mesh = make_mesh(dp=args.dp, ep=args.ep)
    cfg = MoEGPTConfig(vocab_size=128, num_layers=2, num_heads=4,
                       head_dim=8, max_seq_len=64,
                       num_experts=args.experts, mesh=mesh,
                       router_top_k=args.top_k,
                       dtype=jnp.float32, attention_impl="reference")
    model = MoEGPT(cfg)

    r = np.random.RandomState(0)
    toks = jnp.asarray(r.randint(0, 128, (2 * args.dp, 32)), jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)

    variables = model.init(jax.random.PRNGKey(0), toks)
    rules = moe_partition_rules()
    params = shard_params(variables["params"], mesh, rules)
    tx = optax.adamw(1e-3)
    opt = tx.init(params)
    step = make_gspmd_train_step(model.apply, tx, mesh, rules,
                                 batch_spec=P("dp", None),
                                 aux_loss_fn=moe_aux_loss)

    for s in range(args.steps):
        params, opt, loss = step(params, opt, toks, tgts)
        print(f"step {s}: moe loss={float(loss):.4f} "
              f"(experts sharded {args.ep}-way)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
