"""Uneven-data DP training with hvd.join (zero-fill semantics).

Reference scenario (test/parallel/test_torch.py test_horovod_join_allreduce
+ docs join op): ranks have different numbers of batches; a rank that runs
out keeps contributing ZEROS to the gradient allreduce (Average still
divides by the full size) until everyone is done.

Single-controller flavor: device ranks are rows of the stacked batch, so
"rank k ran out" becomes `hvd.join(rank=k)` — subsequent allreduces
zero-fill row k. The multi-process flavor (each process calls bare
`hvd.join()` when its loader is exhausted) is exercised by
tests/data/mp_join_worker.py.

Run: python examples/join_uneven_data.py
"""
import numpy as np

from _common import maybe_cpu_mesh

maybe_cpu_mesh()

import horovod_tpu as hvd  # noqa: E402


def main():
    hvd.init()
    n = hvd.size()
    rng = np.random.RandomState(0)

    # rank r has (r + 1) batches of gradients — maximally uneven
    batches_per_rank = np.arange(1, n + 1)
    max_batches = int(batches_per_rank.max())

    w = np.zeros((n, 4), np.float32)            # replicated "weights"
    for step in range(max_batches):
        # ranks whose data ran out join before this step
        for r in range(n):
            if batches_per_rank[r] == step:
                hvd.join(rank=r)
        grads = rng.rand(n, 4).astype(np.float32)
        avg = np.asarray(hvd.allreduce(grads, hvd.Average,
                                       name=f"grad_{step}"))
        active = int((batches_per_rank > step).sum())
        print(f"step {step}: {active}/{n} ranks active, "
              f"grad mean {float(avg.mean()):.4f}")
        w -= 0.1 * avg

    last = hvd.join()                           # everyone joined; reset
    print(f"all ranks joined; last joined rank = {last}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
