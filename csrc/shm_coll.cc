// Native shared-memory CPU collectives for local multi-process jobs.
//
// TPU-native re-design of the reference's CPU data plane
// (horovod/common/ops/gloo_operations.cc — ring/halving-doubling allreduce,
// allgatherv, broadcast over Gloo). On one host the fastest transport is not
// a socket ring but the page cache: ranks map one POSIX shm segment laid out
// as [header | per-rank slots | result area] and run a chunked
// reduce-scatter + copy-out:
//
//   copy-in -> barrier -> each rank reduces its 1/size chunk across all
//   slots into the result area (parallel, like the per-local-rank split in
//   NCCLHierarchicalAllreduce, nccl_operations.cc:404-470) -> barrier ->
//   copy-out -> barrier (so nobody overwrites slots for the next call while
//   a peer still reads).
//
// Synchronization is a sense-reversing barrier on std::atomics in the shm
// header with sched_yield backoff — no kernel objects needed beyond the
// segment itself.
#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <string>

namespace {

constexpr uint64_t kMagic = 0x48564453484d0001ull;  // "HVDSHM" v1

enum DType : int {
  DT_F32 = 0, DT_F64 = 1, DT_I32 = 2, DT_I64 = 3,
  DT_F16 = 4  // reduced via software half<->float conversion (the role
              // of the reference's fp16 CPU math, common/half.cc:30-54)
};
enum RedOp : int { OP_SUM = 0, OP_PROD = 1, OP_MIN = 2, OP_MAX = 3 };

struct Header {
  std::atomic<uint64_t> magic;
  std::atomic<uint64_t> gen;  // per-job token: attachers reject stale segments
  uint32_t size;
  uint64_t capacity;
  std::atomic<uint32_t> arrived;
  std::atomic<uint32_t> sense;
  std::atomic<uint32_t> attached;
};

struct Comm {
  Header* hdr = nullptr;
  uint8_t* base = nullptr;   // whole mapping
  size_t map_len = 0;
  int rank = 0, size = 0;
  uint64_t capacity = 0;
  uint32_t local_sense = 0;
  std::string name;
  bool owner = false;

  uint8_t* slot(int r) const {
    return base + sizeof(Header) + static_cast<uint64_t>(r) * capacity;
  }
  uint8_t* result() const {
    return base + sizeof(Header) + static_cast<uint64_t>(size) * capacity;
  }
};

bool deadline_passed(const std::chrono::steady_clock::time_point& dl) {
  return std::chrono::steady_clock::now() > dl;
}

// 0 = ok, 1 = timeout
int barrier(Comm* c, double timeout_s) {
  auto dl = std::chrono::steady_clock::now() +
            std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                std::chrono::duration<double>(timeout_s));
  c->local_sense ^= 1;
  if (c->hdr->arrived.fetch_add(1, std::memory_order_acq_rel) ==
      static_cast<uint32_t>(c->size - 1)) {
    c->hdr->arrived.store(0, std::memory_order_relaxed);
    c->hdr->sense.store(c->local_sense, std::memory_order_release);
    return 0;
  }
  int spins = 0;
  while (c->hdr->sense.load(std::memory_order_acquire) != c->local_sense) {
    if (++spins > 1024) {
      sched_yield();
      spins = 0;
      if (deadline_passed(dl)) return 1;
    }
  }
  return 0;
}

template <typename T>
void reduce_chunk(Comm* c, uint64_t begin, uint64_t end, int op) {
  T* out = reinterpret_cast<T*>(c->result());
  const T* first = reinterpret_cast<const T*>(c->slot(0));
  std::memcpy(out + begin, first + begin, (end - begin) * sizeof(T));
  for (int r = 1; r < c->size; ++r) {
    const T* in = reinterpret_cast<const T*>(c->slot(r));
    switch (op) {
      case OP_SUM:
        for (uint64_t i = begin; i < end; ++i) out[i] += in[i];
        break;
      case OP_PROD:
        for (uint64_t i = begin; i < end; ++i) out[i] *= in[i];
        break;
      case OP_MIN:
        for (uint64_t i = begin; i < end; ++i)
          out[i] = in[i] < out[i] ? in[i] : out[i];
        break;
      case OP_MAX:
        for (uint64_t i = begin; i < end; ++i)
          out[i] = in[i] > out[i] ? in[i] : out[i];
        break;
    }
  }
}

size_t dtype_size(int dtype) {
  switch (dtype) {
    case DT_F16:
      return 2;
    case DT_F32:
    case DT_I32:
      return 4;
    default:
      return 8;
  }
}

// IEEE-754 binary16 <-> binary32, scalar software conversion with
// round-to-nearest-even on the way down (the reference keeps a scalar
// fallback beside its F16C fast path, half.cc:30-54).
inline float half_to_float(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t man = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {  // subnormal half: renormalize into a normal float
      uint32_t e = 113;  // 127 - 15 + 1
      while (!(man & 0x400u)) {
        man <<= 1;
        --e;
      }
      man &= 0x3ffu;
      f = sign | (e << 23) | (man << 13);
    }
  } else if (exp == 31) {  // inf / nan
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp + 112) << 23) | (man << 13);
  }
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

inline uint16_t float_to_half(float v) {
  uint32_t f;
  std::memcpy(&f, &v, 4);
  uint16_t sign = static_cast<uint16_t>((f >> 16) & 0x8000u);
  uint32_t fexp = (f >> 23) & 0xffu;
  uint32_t man = f & 0x7fffffu;
  if (fexp == 0xffu) {  // inf / nan
    return sign | 0x7c00u | (man ? 0x200u : 0u);
  }
  int32_t exp = static_cast<int32_t>(fexp) - 127 + 15;
  if (exp >= 31) return sign | 0x7c00u;  // overflow -> inf
  if (exp <= 0) {                        // subnormal half or zero
    if (exp < -10) return sign;
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint16_t h = static_cast<uint16_t>(man >> shift);
    uint32_t rem = man & ((1u << shift) - 1u);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (h & 1u))) ++h;
    return sign | h;
  }
  uint16_t h = sign | static_cast<uint16_t>(exp << 10) |
               static_cast<uint16_t>(man >> 13);
  uint32_t rem = man & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (h & 1u))) ++h;
  return h;
}

void reduce_chunk_f16(Comm* c, uint64_t begin, uint64_t end, int op) {
  // element-outer with a float accumulator: one rounding per element
  // (rank-outer would re-round per rank, compounding error — e.g.
  // 1024 + 0.4*3 pairwise-rounds to 1024, accumulated rounds to 1025)
  uint16_t* out = reinterpret_cast<uint16_t*>(c->result());
  for (uint64_t i = begin; i < end; ++i) {
    float acc = half_to_float(
        reinterpret_cast<const uint16_t*>(c->slot(0))[i]);
    for (int r = 1; r < c->size; ++r) {
      float b = half_to_float(
          reinterpret_cast<const uint16_t*>(c->slot(r))[i]);
      switch (op) {
        case OP_SUM:
          acc += b;
          break;
        case OP_PROD:
          acc *= b;
          break;
        case OP_MIN:
          acc = b < acc ? b : acc;
          break;
        default:
          acc = b > acc ? b : acc;
          break;
      }
    }
    out[i] = float_to_half(acc);
  }
}

}  // namespace

extern "C" {

// Rank 0 creates + initializes the segment; other ranks attach (retrying
// until the header magic appears). `capacity` bytes per rank slot. `gen` is
// a job-unique token (all ranks pass the same value): attachers reject a
// stale segment left by a crashed previous job whose magic is still set —
// without it a fast-starting rank could join the old segment just before
// rank 0 unlinks it.
void* hvd_shm_create(const char* name, int rank, int size, uint64_t capacity,
                     uint64_t gen, double timeout_s) {
  std::string shm_name = std::string("/") + name;
  size_t map_len =
      sizeof(Header) + (static_cast<size_t>(size) + 1) * capacity;
  auto dl = std::chrono::steady_clock::now() +
            std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                std::chrono::duration<double>(timeout_s));
  void* base = MAP_FAILED;
  if (rank == 0) {
    shm_unlink(shm_name.c_str());  // stale segment from a crashed job
    int fd = shm_open(shm_name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
      close(fd);
      shm_unlink(shm_name.c_str());
      return nullptr;
    }
    base = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (base == MAP_FAILED) return nullptr;
  } else {
    // Attach loop: a stale segment from a crashed previous job may still be
    // linked under this name with magic set, so after mapping we check the
    // generation token and, on mismatch, unmap and re-open — the fresh
    // segment is a different inode, so the old mapping would never update.
    for (;;) {
      int fd = shm_open(shm_name.c_str(), O_RDWR, 0600);
      if (fd >= 0) {
        struct stat st {};
        if (fstat(fd, &st) == 0 &&
            static_cast<size_t>(st.st_size) >= map_len) {
          base = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED,
                      fd, 0);
          close(fd);
          if (base != MAP_FAILED) {
            auto* hdr = reinterpret_cast<Header*>(base);
            auto probe_dl = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(50);
            bool match = false;
            do {
              match =
                  hdr->magic.load(std::memory_order_acquire) == kMagic &&
                  hdr->gen.load(std::memory_order_relaxed) == gen;
            } while (!match && !deadline_passed(probe_dl));
            if (match) {
              // magic is stored with release after size/capacity, so both
              // are valid here; a mismatch is a config/version error, not
              // staleness — fail loudly instead of corrupting offsets.
              if (hdr->size != static_cast<uint32_t>(size) ||
                  hdr->capacity != capacity) {
                munmap(base, map_len);
                return nullptr;
              }
              break;
            }
            munmap(base, map_len);
            base = MAP_FAILED;
          }
        } else {
          close(fd);
        }
      }
      if (deadline_passed(dl)) return nullptr;
      usleep(1000);
    }
  }

  auto* c = new Comm();
  c->base = static_cast<uint8_t*>(base);
  c->map_len = map_len;
  c->hdr = reinterpret_cast<Header*>(base);
  c->rank = rank;
  c->size = size;
  c->capacity = capacity;
  c->name = shm_name;
  c->owner = (rank == 0);

  if (rank == 0) {
    c->hdr->size = static_cast<uint32_t>(size);
    c->hdr->capacity = capacity;
    c->hdr->arrived.store(0);
    c->hdr->sense.store(0);
    c->hdr->attached.store(0);
    c->hdr->gen.store(gen, std::memory_order_relaxed);
    c->hdr->magic.store(kMagic, std::memory_order_release);
  }
  c->hdr->attached.fetch_add(1);
  // join barrier: everyone mapped before anyone proceeds
  while (c->hdr->attached.load(std::memory_order_acquire) <
         static_cast<uint32_t>(size)) {
    if (deadline_passed(dl)) {
      munmap(base, map_len);
      delete c;
      return nullptr;
    }
    usleep(1000);
  }
  return c;
}

void hvd_shm_destroy(void* h) {
  auto* c = static_cast<Comm*>(h);
  if (!c) return;
  if (c->base) munmap(c->base, c->map_len);
  if (c->owner) shm_unlink(c->name.c_str());
  delete c;
}

int hvd_shm_barrier(void* h, double timeout_s) {
  return barrier(static_cast<Comm*>(h), timeout_s);
}

// In-place allreduce over all ranks. Chunked: each rank reduces an equal
// share into the shared result area; all copy the full result out.
int hvd_shm_allreduce(void* h, void* data, uint64_t count, int dtype, int op,
                      double timeout_s) {
  auto* c = static_cast<Comm*>(h);
  // validate before the first barrier: a mid-protocol return would
  // desynchronize the sense-reversing barrier for every peer
  if (dtype < DT_F32 || dtype > DT_F16) return 3;
  size_t esize = dtype_size(dtype);
  uint64_t bytes = count * esize;
  if (bytes > c->capacity) return 2;
  std::memcpy(c->slot(c->rank), data, bytes);
  if (barrier(c, timeout_s)) return 1;

  uint64_t chunk = (count + c->size - 1) / c->size;
  uint64_t begin = std::min<uint64_t>(chunk * c->rank, count);
  uint64_t end = std::min<uint64_t>(begin + chunk, count);
  if (end > begin) {
    switch (dtype) {
      case DT_F32:
        reduce_chunk<float>(c, begin, end, op);
        break;
      case DT_F64:
        reduce_chunk<double>(c, begin, end, op);
        break;
      case DT_I32:
        reduce_chunk<int32_t>(c, begin, end, op);
        break;
      case DT_I64:
        reduce_chunk<int64_t>(c, begin, end, op);
        break;
      case DT_F16:
        reduce_chunk_f16(c, begin, end, op);
        break;
      default:
        return 3;
    }
  }
  if (barrier(c, timeout_s)) return 1;
  std::memcpy(data, c->result(), bytes);
  // third barrier: nobody may start the next collective (overwriting slots /
  // result) until everyone has copied out
  if (barrier(c, timeout_s)) return 1;
  return 0;
}

// Uniform-size allgather: out receives size*bytes, rank order.
int hvd_shm_allgather(void* h, const void* in, uint64_t bytes, void* out,
                      double timeout_s) {
  auto* c = static_cast<Comm*>(h);
  if (bytes > c->capacity) return 2;
  std::memcpy(c->slot(c->rank), in, bytes);
  if (barrier(c, timeout_s)) return 1;
  for (int r = 0; r < c->size; ++r)
    std::memcpy(static_cast<uint8_t*>(out) + static_cast<uint64_t>(r) * bytes,
                c->slot(r), bytes);
  if (barrier(c, timeout_s)) return 1;
  return 0;
}

// In-place broadcast from root.
int hvd_shm_broadcast(void* h, void* data, uint64_t bytes, int root,
                      double timeout_s) {
  auto* c = static_cast<Comm*>(h);
  if (bytes > c->capacity) return 2;
  if (c->rank == root) std::memcpy(c->slot(root), data, bytes);
  if (barrier(c, timeout_s)) return 1;
  if (c->rank != root) std::memcpy(data, c->slot(root), bytes);
  if (barrier(c, timeout_s)) return 1;
  return 0;
}

// Reduce-scatter: rank i receives the reduced chunk i (equal chunks of
// count/size elements; count must be divisible by size). out holds
// count/size elements.
int hvd_shm_reducescatter(void* h, const void* in, void* out, uint64_t count,
                          int dtype, int op, double timeout_s) {
  auto* c = static_cast<Comm*>(h);
  if (count % c->size != 0) return 4;
  if (dtype < DT_F32 || dtype > DT_F16) return 3;
  size_t esize = dtype_size(dtype);
  if (count * esize > c->capacity) return 2;
  std::memcpy(c->slot(c->rank), in, count * esize);
  if (barrier(c, timeout_s)) return 1;
  uint64_t chunk = count / c->size;
  uint64_t begin = chunk * c->rank, end = begin + chunk;
  switch (dtype) {
    case DT_F32:
      reduce_chunk<float>(c, begin, end, op);
      break;
    case DT_F64:
      reduce_chunk<double>(c, begin, end, op);
      break;
    case DT_I32:
      reduce_chunk<int32_t>(c, begin, end, op);
      break;
    case DT_I64:
      reduce_chunk<int64_t>(c, begin, end, op);
      break;
    case DT_F16:
      reduce_chunk_f16(c, begin, end, op);
      break;
    default:
      return 3;
  }
  if (barrier(c, timeout_s)) return 1;
  std::memcpy(out, c->result() + begin * esize, chunk * esize);
  if (barrier(c, timeout_s)) return 1;
  return 0;
}

}  // extern "C"
