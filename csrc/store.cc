// Native coordination layer: TCP key-value store + rank-0-free coordinator
// primitives (barrier / allgather / broadcast / bitwise AND-OR of bitvectors).
//
// TPU-native re-design of the reference's control-plane transport:
//  * horovod/common/gloo/http_store.{cc,h} — HTTP KV rendezvous store the C++
//    core uses to bootstrap Gloo contexts. Here the store speaks a compact
//    length-prefixed binary protocol instead of HTTP, and supports blocking
//    GET with timeout plus read-counted auto-deletion (the role of the
//    reference's DELETE-based finalization scopes, runner/http/http_server.py).
//  * horovod/common/controller.h:49-157 — the pure-virtual transport hooks
//    (CrossRankBitwiseAnd/Or, Bcast, Barrier, SendReadyTensors, ...) that MPI
//    and Gloo controllers implement. hvd_coord_* provides the same primitive
//    set over the store so the Python negotiation layer can agree on cache
//    bitvectors across processes exactly like ComputeResponseList's fast path
//    (controller.cc:155-190) without MPI or Gloo.
//
// Design notes: the control plane is low-fan-out (one connection per process)
// and latency-bound, so the server is thread-per-connection with a condvar'd
// map; collectives are store-key based with an internal sequence number so
// repeated calls on the same tag never collide.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t {
  OP_SET = 1,
  OP_GET = 2,      // blocking, with timeout; optional read-counted delete
  OP_DEL = 3,
  OP_PING = 4,
  OP_GATHER = 5,   // join-and-collect: post a blob, reply with all blobs
  OP_STAT = 6,     // introspection: entry/gather counts (leak checks)
  OP_REDUCE = 7,   // join-and-reduce: post a blob, reply with the
                   // bitwise AND/OR of all members' blobs — the
                   // negotiation bitvector fast path. Unlike OP_GATHER
                   // the reply is O(blob), not O(P*blob): at P=64 the
                   // gather reply fan-out alone busts the ~1 ms cadence
                   // budget (benchmarks/store_service_time.py)
};

enum Status : uint8_t {
  ST_OK = 0,
  ST_TIMEOUT = 1,
  ST_ERROR = 2,
  ST_AGAIN = 3,  // client-side: result larger than the caller's buffer,
                 // stashed in the client — take with take_pending
  ST_CONN = 4,   // client-side: the TRANSPORT to the server failed
                 // (send/recv on a broken socket). Distinct from
                 // ST_ERROR — a server-reported protocol error — so the
                 // Python retry ladder can classify: connection faults
                 // are retryable after Reconnect(), protocol errors and
                 // timeouts are not.
};

bool send_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool send_frame(int fd, uint8_t status, const std::string& payload) {
  // single vectored syscall per reply — a second send of the tiny
  // header measurably dominates small-reply service time
  // (benchmarks/store_service_time.py), and copying the payload into a
  // header-prefixed buffer would cost O(P·blob) per gather reply
  uint32_t len = static_cast<uint32_t>(payload.size());
  char hdr[5];
  hdr[0] = static_cast<char>(status);
  std::memcpy(hdr + 1, &len, 4);
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = 5;
  iov[1].iov_base = const_cast<char*>(payload.data());
  iov[1].iov_len = payload.size();
  size_t total = 5 + payload.size();
  size_t sent = 0;
  int iovcnt = payload.empty() ? 1 : 2;
  while (sent < total) {
    // sendmsg, not writev: replies to DEAD clients are normal here (a
    // handler that timed out waiting on a crashed peer still replies),
    // and only msg-family syscalls take MSG_NOSIGNAL — a raw writev
    // would raise SIGPIPE and kill the embedding process
    struct msghdr msg {};
    msg.msg_iov = iov;
    msg.msg_iovlen = static_cast<size_t>(iovcnt);
    ssize_t n = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    sent += static_cast<size_t>(n);
    // advance the iovecs past what the kernel took (partial writes)
    size_t done = static_cast<size_t>(n);
    for (int i = 0; i < iovcnt && done > 0; ++i) {
      size_t take = iov[i].iov_len < done ? iov[i].iov_len : done;
      iov[i].iov_base = static_cast<char*>(iov[i].iov_base) + take;
      iov[i].iov_len -= take;
      done -= take;
    }
  }
  return true;
}

// Per-connection buffered reader: one ::recv refill per (small) request
// instead of five header-sized recvs — each tiny recv is a full syscall
// and the store's per-request service time is syscall-bound.
class BufReader {
 public:
  explicit BufReader(int fd) : fd_(fd) {}

  bool ReadExact(void* out, size_t len) {
    char* p = static_cast<char*>(out);
    while (len > 0) {
      size_t avail = end_ - pos_;
      if (avail == 0) {
        // large-payload bypass: nothing buffered and the remainder
        // exceeds the buffer — recv straight into the destination, no
        // staging copy and no 16 KB syscall cap
        if (len >= sizeof(buf_)) return recv_all(fd_, p, len);
        if (!Refill()) return false;
        continue;
      }
      size_t take = avail < len ? avail : len;
      std::memcpy(p, buf_ + pos_, take);
      pos_ += take;
      p += take;
      len -= take;
    }
    return true;
  }

 private:
  bool Refill() {
    pos_ = end_ = 0;
    for (;;) {
      ssize_t n = ::recv(fd_, buf_, sizeof(buf_), 0);
      if (n > 0) {
        end_ = static_cast<size_t>(n);
        return true;
      }
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
  }

  int fd_;
  char buf_[16384];
  size_t pos_ = 0, end_ = 0;
};

// Is the requesting connection still alive? A cheap nonblocking peek:
// orderly EOF or a hard error means the client died and nobody will read
// our reply — the handler must stop waiting on its behalf.
bool peer_alive(int fd) {
  char b;
  ssize_t n = ::recv(fd, &b, 1, MSG_PEEK | MSG_DONTWAIT);
  if (n == 0) return false;  // EOF
  if (n < 0)
    return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
  return true;  // pipelined next request already queued: alive
}

struct Entry {
  std::string value;
  int reads_left = 0;  // 0 = persistent; >0 = erase after this many reads
  bool present = false;
  // retry bookkeeping for read-counted entries (broadcast fan-out):
  // nonces whose read slot was already consumed — a replayed Get (same
  // nonce, its reply lost to a connection break) is served the value
  // again WITHOUT a second decrement, so a one-rank blip can never
  // erase the key early and starve another reader into a timeout
  std::set<uint64_t> served;
  std::chrono::steady_clock::time_point touch;  // for the TTL sweep
};

struct ReduceState {
  std::set<int> posted;  // ranks folded into acc (idempotent re-posts)
  std::string acc;       // running AND/OR accumulator
  uint8_t kind = 0;      // 0 = AND, 1 = OR (first post decides; a
                         // non-first post that disagrees is a protocol
                         // error, same as a size mismatch)
  bool complete = false;
  int reads_left = 0;
  int waiters = 0;
  // retry bookkeeping (the reconnect-and-replay ladder): the nonce each
  // member's logical request carried — a replayed post (same rank, same
  // nonce) after its read slot was consumed is served the result again
  // instead of consuming a second slot or starting a phantom round
  std::map<int, uint64_t> nonces;
  std::set<int> served;  // ranks whose read slot was consumed
  std::chrono::steady_clock::time_point touch;
};

struct GatherState {
  std::map<int, std::string> blobs;  // rank -> posted blob (pre-complete)
  std::string result;                // concat, set at completion
  bool complete = false;
  int reads_left = 0;                // erase after every member read it
  int waiters = 0;                   // handlers blocked on this round —
                                     // the sweep must not pull state out
                                     // from under a live (possibly
                                     // infinite-timeout) waiter
  std::map<int, uint64_t> nonces;    // see ReduceState::nonces
  std::set<int> served;
  std::chrono::steady_clock::time_point touch;  // for the TTL sweep
};

// A fully drained join round, kept briefly so a member whose reply was
// lost to a connection break can re-post (same rank + nonce) and be
// served the result instead of opening a phantom new round that would
// hang every future caller of the key. Bounded by count and TTL; the
// nonce check means a genuinely NEW round on a reused key (per-tag seqs
// can restart after the tag_seq_ prune) falls through to the live path.
struct DoneRound {
  std::string result;
  std::map<int, uint64_t> nonces;
  std::set<uint64_t> get_served;  // read-counted Get replays (no rank
                                  // on that wire op; the nonce alone
                                  // identifies the logical request)
  std::chrono::steady_clock::time_point t;
};

class StoreServer {
 public:
  explicit StoreServer(int port) {
    // Orphaned-state TTL (seconds): read-counted entries and gather
    // rounds whose readers died can never hit reads_left == 0 on their
    // own; the sweep expires them so a member crash does not leak state
    // for the server's lifetime. Generous default — well above every
    // client-side timeout — so no live waiter ever sees its state
    // swept from under it.
    const char* ttl = std::getenv("HVD_STORE_STATE_TTL_S");
    double ttl_s = ttl ? std::atof(ttl) : 900.0;
    // malformed values (atof -> 0) must not turn the sweep into a
    // destroy-everything loop; fall back to the default
    if (!(ttl_s > 0.0) || !std::isfinite(ttl_s)) ttl_s = 900.0;
    state_ttl_ = std::chrono::duration<double>(ttl_s);
    last_sweep_ = std::chrono::steady_clock::now();
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
            0 ||
        ::listen(listen_fd_, 512) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~StoreServer() {
    shutting_down_.store(true);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    {
      std::lock_guard<std::mutex> lk(mu_);
      cv_.notify_all();
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : handlers_)
      if (t.joinable()) t.join();
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  int port() const { return port_; }
  bool ok() const { return listen_fd_ >= 0; }

 private:
  void AcceptLoop() {
    while (!shutting_down_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (errno == EINTR) continue;
        break;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu_);
      if (shutting_down_.load()) {
        ::close(fd);
        break;
      }
      conn_fds_.insert(fd);
      handlers_.emplace_back([this, fd] { Handle(fd); });
    }
  }

  void Handle(int fd) {
    BufReader rd(fd);
    for (;;) {
      uint8_t op;
      uint32_t klen, vlen;
      if (!rd.ReadExact(&op, 1) || !rd.ReadExact(&klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !rd.ReadExact(&key[0], klen)) break;
      if (!rd.ReadExact(&vlen, 4)) break;
      std::string val(vlen, '\0');
      if (vlen && !rd.ReadExact(&val[0], vlen)) break;

      bool alive = true;
      switch (op) {
        case OP_SET: {
          {
            std::lock_guard<std::mutex> lk(mu_);
            SweepLocked(false);
            auto& e = data_[key];
            if (e.present && e.reads_left > 0 && e.value == val) {
              // an identical re-Set while a read-counted drain is in
              // flight is a transport replay (the Set's reply was lost,
              // the ladder re-posted): keep the drain's bookkeeping —
              // resetting it would re-arm reads_left past the
              // remaining readers and leak the entry until the TTL
              e.touch = std::chrono::steady_clock::now();
            } else {
              e.value = std::move(val);
              e.present = true;
              e.reads_left = 0;
              e.served.clear();  // a re-Set key starts a fresh round:
                                 // old replay nonces must not shadow it
              e.touch = std::chrono::steady_clock::now();
            }
          }
          cv_.notify_all();
          alive = send_frame(fd, ST_OK, "");
          break;
        }
        case OP_GET: {
          // value payload: double timeout_s + int32 expected_reads
          // [+ u64 nonce — identifies the LOGICAL request across
          // transport retries (reconnect-and-replay); 0/absent =
          // legacy, no dedupe]
          double timeout_s = -1.0;
          int32_t expected = 0;
          uint64_t nonce = 0;
          if (val.size() >= 12) {
            std::memcpy(&timeout_s, val.data(), 8);
            std::memcpy(&expected, val.data() + 8, 4);
          }
          if (val.size() >= 20) std::memcpy(&nonce, val.data() + 12, 8);
          std::unique_lock<std::mutex> lk(mu_);
          auto replay_done = [&]() -> DoneRound* {
            // a replay of the FINAL read: the entry was erased by this
            // very nonce's first (reply-lost) pass — serve the retained
            // value instead of blocking for a value that will never
            // reappear
            if (expected <= 0 || nonce == 0) return nullptr;
            auto dit = done_.find(key);
            return (dit != done_.end() && dit->second.get_served.count(nonce))
                       ? &dit->second
                       : nullptr;
          };
          auto ready = [&] {
            auto it = data_.find(key);
            return (it != data_.end() && it->second.present) ||
                   replay_done() != nullptr || shutting_down_.load();
          };
          bool got = WaitPred(lk, timeout_s, fd, ready) &&
                     !shutting_down_.load();
          if (!got) {
            lk.unlock();
            alive = send_frame(fd, ST_TIMEOUT, "");
            break;
          }
          std::string out;
          if (DoneRound* d = replay_done()) {
            out = d->result;
          } else {
            auto it = data_.find(key);
            out = it->second.value;
            if (expected > 0) {
              Entry& e = it->second;
              // consume a read slot only ONCE per logical request: a
              // replayed Get whose first reply was lost must not eat
              // another reader's slot (the gather/reduce rule)
              bool fresh = nonce == 0 || e.served.insert(nonce).second;
              if (fresh) {
                if (e.reads_left == 0) e.reads_left = expected;
                e.touch = std::chrono::steady_clock::now();
                if (--e.reads_left == 0) {
                  DoneRound d;
                  d.result = std::move(e.value);
                  d.get_served = std::move(e.served);
                  d.t = std::chrono::steady_clock::now();
                  done_[key] = std::move(d);
                  PruneDoneLocked();
                  data_.erase(it);
                }
              }
            }
          }
          lk.unlock();
          alive = send_frame(fd, ST_OK, out);
          break;
        }
        case OP_DEL: {
          {
            std::lock_guard<std::mutex> lk(mu_);
            data_.erase(key);
          }
          alive = send_frame(fd, ST_OK, "");
          break;
        }
        case OP_PING:
          alive = send_frame(fd, ST_OK, "pong");
          break;
        case OP_GATHER: {
          // Server-side allgather: ONE round trip per member per round
          // (the client-side loop of per-rank Gets was O(P) sequential
          // RTTs — ~140 ms/round at P=64; this is the fan-in the
          // reference controller does at the coordinator rank,
          // controller.cc:124 RecvReadyTensors).
          // value payload: double timeout_s + i32 group size + i32 rank
          // + u64 nonce + blob. Reply: concat of u32-len-prefixed blobs
          // rank-order. The nonce identifies the LOGICAL request across
          // transport retries (reconnect-and-replay).
          if (val.size() < 24) {
            alive = send_frame(fd, ST_ERROR, "bad gather");
            break;
          }
          double timeout_s;
          int32_t gsize, grank;
          uint64_t nonce;
          std::memcpy(&timeout_s, val.data(), 8);
          std::memcpy(&gsize, val.data() + 8, 4);
          std::memcpy(&grank, val.data() + 12, 4);
          std::memcpy(&nonce, val.data() + 16, 8);
          if (gsize <= 0 || grank < 0 || grank >= gsize) {
            alive = send_frame(fd, ST_ERROR, "bad gather args");
            break;
          }
          alive = JoinRound(
              fd, gathers_, &svc_gather_, key, timeout_s, grank, nonce,
              [&](GatherState& g) -> const char* {
                if (g.complete) return nullptr;
                // idempotent re-post (a member retrying after timeout)
                g.blobs[grank] = val.substr(24);
                g.nonces[grank] = nonce;
                if (static_cast<int>(g.blobs.size()) == gsize) {
                  std::string res;
                  for (auto& kv : g.blobs) {
                    uint32_t blen =
                        static_cast<uint32_t>(kv.second.size());
                    res.append(reinterpret_cast<char*>(&blen), 4);
                    res.append(kv.second);
                  }
                  g.result = std::move(res);
                  g.blobs.clear();
                  CompleteLocked(g, gsize);
                }
                return nullptr;
              },
              [](GatherState& g) -> std::string& { return g.result; });
          break;
        }
        case OP_REDUCE: {
          // value payload: double timeout_s + i32 group size + i32 rank
          // + u64 nonce + u8 kind (0 AND / 1 OR) + blob. Reply: the
          // reduced blob.
          if (val.size() < 25) {
            alive = send_frame(fd, ST_ERROR, "bad reduce");
            break;
          }
          double timeout_s;
          int32_t gsize, grank;
          uint64_t nonce;
          uint8_t kind;
          std::memcpy(&timeout_s, val.data(), 8);
          std::memcpy(&gsize, val.data() + 8, 4);
          std::memcpy(&grank, val.data() + 12, 4);
          std::memcpy(&nonce, val.data() + 16, 8);
          kind = static_cast<uint8_t>(val[24]);
          if (gsize <= 0 || grank < 0 || grank >= gsize || kind > 1) {
            alive = send_frame(fd, ST_ERROR, "bad reduce args");
            break;
          }
          alive = JoinRound(
              fd, reduces_, &svc_reduce_, key, timeout_s, grank, nonce,
              [&](ReduceState& r) -> const char* {
                if (r.complete) return nullptr;
                // refresh the nonce on EVERY re-post (gather's rule):
                // a timeout retry is a new logical request with a new
                // nonce, and the done-round cache must be keyed by the
                // LATEST one — a stale nonce would let that retry's
                // replay erase the cache and open a phantom round
                r.nonces[grank] = nonce;
                if (r.posted.count(grank)) return nullptr;
                const char* blob = val.data() + 25;
                size_t blen = val.size() - 25;
                if (r.posted.empty()) {
                  r.acc.assign(blob, blen);
                  r.kind = kind;
                } else if (kind != r.kind) {
                  // protocol error, like the size-mismatch path below:
                  // silently applying the first poster's kind would
                  // hand a member an AND where it asked for an OR
                  return "reduce kind mismatch";
                } else if (blen != r.acc.size()) {
                  return "reduce size mismatch";
                } else {
                  uint8_t* a = reinterpret_cast<uint8_t*>(&r.acc[0]);
                  const uint8_t* b =
                      reinterpret_cast<const uint8_t*>(blob);
                  if (r.kind == 0)
                    for (size_t i = 0; i < blen; ++i) a[i] &= b[i];
                  else
                    for (size_t i = 0; i < blen; ++i) a[i] |= b[i];
                }
                r.posted.insert(grank);
                if (static_cast<int>(r.posted.size()) == gsize)
                  CompleteLocked(r, gsize);
                return nullptr;
              },
              [](ReduceState& r) -> std::string& { return r.acc; });
          break;
        }
        case OP_STAT: {
          // leak introspection: sweep (ignoring the rate guard) and
          // report live state counts — the restart-after-dead-member
          // test asserts gathers=0 here
          std::unique_lock<std::mutex> lk(mu_);
          SweepLocked(true);
          std::string st = "data=" + std::to_string(data_.size()) +
                           " gathers=" + std::to_string(gathers_.size()) +
                           " reduces=" + std::to_string(reduces_.size()) +
                           " done=" + std::to_string(done_.size()) +
                           " svc_gather_n=" +
                           std::to_string(svc_gather_.n.load()) +
                           " svc_gather_ns=" +
                           std::to_string(svc_gather_.work_ns.load()) +
                           " svc_gather_max_ns=" +
                           std::to_string(svc_gather_.max_ns.load()) +
                           " svc_gather_send_ns=" +
                           std::to_string(svc_gather_.send_ns.load()) +
                           " svc_reduce_n=" +
                           std::to_string(svc_reduce_.n.load()) +
                           " svc_reduce_ns=" +
                           std::to_string(svc_reduce_.work_ns.load()) +
                           " svc_reduce_max_ns=" +
                           std::to_string(svc_reduce_.max_ns.load()) +
                           " svc_reduce_send_ns=" +
                           std::to_string(svc_reduce_.send_ns.load());
          lk.unlock();
          alive = send_frame(fd, ST_OK, st);
          break;
        }
        default:
          alive = send_frame(fd, ST_ERROR, "bad op");
      }
      if (!alive) break;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      conn_fds_.erase(fd);
    }
    ::close(fd);
  }

  // Wait under lk until pred, honoring timeout_s (< 0 = unbounded), but
  // bail out when the REQUESTING connection dies: a handler blocked
  // forever on behalf of a dead peer would leak its thread — and for
  // gathers, pin (sweep-proof) the round state — for the server's
  // lifetime. Returns pred()'s final value.
  template <typename Pred>
  bool WaitPred(std::unique_lock<std::mutex>& lk, double timeout_s, int fd,
                Pred pred) {
    using clock = std::chrono::steady_clock;
    const clock::duration slice = std::chrono::seconds(15);
    clock::time_point deadline;
    if (timeout_s >= 0)
      deadline = clock::now() + std::chrono::duration_cast<clock::duration>(
                                    std::chrono::duration<double>(timeout_s));
    for (;;) {
      clock::duration wait = slice;
      if (timeout_s >= 0) {
        auto left = deadline - clock::now();
        if (left <= clock::duration::zero()) return pred();
        if (left < wait) wait = left;
      }
      if (cv_.wait_for(lk, wait, pred)) return true;
      if (!peer_alive(fd)) return false;  // requester died
    }
  }

  struct SvcCounters {
    std::atomic<uint64_t> work_ns{0};
    std::atomic<uint64_t> send_ns{0};  // reply syscall time, separately:
                                       // the syscall itself is server
                                       // CPU, but it can also absorb
                                       // TCP drain blocking on a slow
                                       // client — keeping it out of
                                       // work_ns keeps that span
                                       // scheduling-noise-free
    std::atomic<uint64_t> n{0};
    std::atomic<uint64_t> max_ns{0};
  };

  void RecordSend(SvcCounters* c,
                  std::chrono::steady_clock::time_point t0) {
    c->send_ns.fetch_add(static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count()), std::memory_order_relaxed);
  }

  // Fold one handler's work spans (pre-wait + post-wake-until-unlock,
  // excluding lock/condvar waits AND the reply send — draining a reply
  // into a slow client's socket is the client's wait, not server work)
  // into a set of service-time counters.
  void RecordSvc(SvcCounters* c, uint64_t pre_ns,
                 std::chrono::steady_clock::time_point w2_start,
                 std::chrono::steady_clock::time_point w2_end) {
    uint64_t ns = pre_ns + static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            w2_end - w2_start)
            .count());
    c->work_ns.fetch_add(ns, std::memory_order_relaxed);
    c->n.fetch_add(1, std::memory_order_relaxed);
    uint64_t prev = c->max_ns.load(std::memory_order_relaxed);
    while (ns > prev && !c->max_ns.compare_exchange_weak(
                            prev, ns, std::memory_order_relaxed)) {
    }
  }

  // mu_ held. Mark a join-round complete and wake its waiters.
  template <typename State>
  void CompleteLocked(State& st, int gsize) {
    st.complete = true;
    st.reads_left = gsize;
    cv_.notify_all();
  }

  // Shared join-round skeleton for OP_GATHER / OP_REDUCE: post/merge
  // under the lock, wait for round completion (requester-death aware,
  // TTL-sweep pinned), drain one read slot, reply. The service-time
  // spans are measured HERE so the two ops' counters stay comparable by
  // construction: only the handler's WORK (post/merge under the lock +
  // result copy) counts — never mutex acquisition, the rate-guarded
  // sweep, the condvar wait for other members, or the reply-send
  // syscall (recorded separately; it can absorb TCP drain blocking) —
  // so the measurement stays meaningful on an oversubscribed host where
  // wait times are scheduling noise (docs/benchmarks.md round-5
  // control-plane isolation).
  //
  // `post(state)` folds this member's payload in (completing the round
  // when it is the last member); it returns nullptr or a protocol-error
  // message. `result(state)` yields the completed round's reply.
  //
  // Replay semantics (the reconnect-and-replay ladder): `grank`/`nonce`
  // identify the member's LOGICAL request across transport retries. A
  // re-post after the member's read slot was already consumed (its
  // reply was lost on the wire) is served the round result again
  // without consuming another slot; a re-post after the round fully
  // drained is served from the bounded done-round cache instead of
  // opening a phantom new round under the same key.
  template <typename StateMap, typename Post, typename Result>
  bool JoinRound(int fd, StateMap& states, SvcCounters* svc,
                 const std::string& key, double timeout_s, int grank,
                 uint64_t nonce, Post post, Result result) {
    std::unique_lock<std::mutex> lk(mu_);
    SweepLocked(false);
    auto dit = done_.find(key);
    if (dit != done_.end()) {
      auto nit = dit->second.nonces.find(grank);
      if (nit != dit->second.nonces.end() && nit->second == nonce) {
        std::string out = dit->second.result;
        dit->second.t = std::chrono::steady_clock::now();
        lk.unlock();
        return send_frame(fd, ST_OK, out);
      }
      // different nonce: a genuinely new round reusing the key — the
      // stale cache entry must not shadow it
      done_.erase(dit);
    }
    auto svc_w1 = std::chrono::steady_clock::now();
    auto& st = states[key];
    st.touch = svc_w1;
    const char* err = post(st);
    if (err != nullptr) {
      lk.unlock();
      return send_frame(fd, ST_ERROR, err);
    }
    auto ready = [&] {
      auto it = states.find(key);
      return (it != states.end() && it->second.complete) ||
             shutting_down_.load();
    };
    st.waiters++;  // pin against the TTL sweep while blocked
    uint64_t svc_pre_ns = static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - svc_w1)
            .count());
    bool got = WaitPred(lk, timeout_s, fd, ready) &&
               !shutting_down_.load();
    auto svc_w2 = std::chrono::steady_clock::now();
    auto it = states.find(key);
    if (it != states.end()) {
      it->second.waiters--;
      it->second.touch = std::chrono::steady_clock::now();
    }
    if (!got) {
      lk.unlock();
      RecordSvc(svc, svc_pre_ns, svc_w2,
                std::chrono::steady_clock::now());
      auto ts = std::chrono::steady_clock::now();
      bool alive = send_frame(fd, ST_TIMEOUT, "");
      RecordSend(svc, ts);
      return alive;
    }
    std::string out = result(it->second);
    // consume a read slot only ONCE per member: a replayed request
    // whose first reply was lost must not eat another member's slot
    if (it->second.served.insert(grank).second &&
        --it->second.reads_left == 0) {
      DoneRound d;
      d.result = out;
      d.nonces = std::move(it->second.nonces);
      d.t = std::chrono::steady_clock::now();
      done_[key] = std::move(d);
      PruneDoneLocked();
      states.erase(it);
    }
    lk.unlock();
    RecordSvc(svc, svc_pre_ns, svc_w2, std::chrono::steady_clock::now());
    auto ts = std::chrono::steady_clock::now();
    bool alive = send_frame(fd, ST_OK, out);
    RecordSend(svc, ts);
    return alive;
  }

  // mu_ held. Bound the done-round replay cache by count (TTL expiry
  // lives in SweepLocked). Oldest-first eviction: a round old enough to
  // be evicted is past every client's retry budget.
  void PruneDoneLocked() {
    const size_t kDoneCap = 256;
    while (done_.size() > kDoneCap) {
      auto oldest = done_.begin();
      for (auto it = done_.begin(); it != done_.end(); ++it)
        if (it->second.t < oldest->second.t) oldest = it;
      done_.erase(oldest);
    }
  }

  // mu_ held. Expire orphaned state: read-counted entries and gather
  // rounds whose remaining readers died (reads_left can never reach 0),
  // and gather rounds that never completed (a member crashed before
  // joining). Live waiters are unaffected: the TTL is far above every
  // client timeout, and a swept incomplete gather just times out its
  // (already doomed) waiter cleanly.
  void SweepLocked(bool force) {
    auto now = std::chrono::steady_clock::now();
    if (!force && now - last_sweep_ < state_ttl_ / 10) return;
    last_sweep_ = now;
    for (auto it = data_.begin(); it != data_.end();) {
      if (it->second.reads_left > 0 && now - it->second.touch > state_ttl_)
        it = data_.erase(it);
      else
        ++it;
    }
    for (auto it = gathers_.begin(); it != gathers_.end();) {
      if (it->second.waiters == 0 && now - it->second.touch > state_ttl_)
        it = gathers_.erase(it);
      else
        ++it;
    }
    for (auto it = reduces_.begin(); it != reduces_.end();) {
      if (it->second.waiters == 0 && now - it->second.touch > state_ttl_)
        it = reduces_.erase(it);
      else
        ++it;
    }
    // done-round replay cache: only useful within a client retry
    // budget, so its TTL is much shorter than the orphan sweep's
    for (auto it = done_.begin(); it != done_.end();) {
      if (now - it->second.t > done_ttl_)
        it = done_.erase(it);
      else
        ++it;
    }
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  std::vector<std::thread> handlers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, Entry> data_;
  std::map<std::string, GatherState> gathers_;
  std::map<std::string, ReduceState> reduces_;
  std::map<std::string, DoneRound> done_;
  std::set<int> conn_fds_;
  std::chrono::duration<double> state_ttl_{900.0};
  std::chrono::duration<double> done_ttl_{120.0};
  std::chrono::steady_clock::time_point last_sweep_;
  // per-op service-time counters (work only; see RecordSvc)
  SvcCounters svc_gather_;
  SvcCounters svc_reduce_;
};

class StoreClient {
 public:
  StoreClient(const std::string& host, int port) : host_(host), port_(port) {
    Connect();
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  // Re-dial the server after a transport failure (ST_CONN). The old
  // socket — if any — is abandoned first: the server's handler observes
  // EOF and cleans up its end. Safe to call repeatedly; returns whether
  // the new connection came up.
  bool Reconnect() {
    std::lock_guard<std::mutex> lk(mu_);
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
    Connect();
    return fd_ >= 0;
  }

  // Returns status; fills out on ST_OK.
  int Request(uint8_t op, const std::string& key, const std::string& val,
              std::string* out) {
    std::lock_guard<std::mutex> lk(mu_);
    if (fd_ < 0) return ST_CONN;
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::string frame;
    frame.reserve(9 + klen + vlen);
    frame.push_back(static_cast<char>(op));
    frame.append(reinterpret_cast<char*>(&klen), 4);
    frame.append(key);
    frame.append(reinterpret_cast<char*>(&vlen), 4);
    frame.append(val);
    if (!send_all(fd_, frame.data(), frame.size())) return Broken();
    uint8_t status;
    uint32_t len;
    if (!recv_all(fd_, &status, 1) || !recv_all(fd_, &len, 4))
      return Broken();
    std::string payload(len, '\0');
    if (len && !recv_all(fd_, &payload[0], len)) return Broken();
    if (out) *out = std::move(payload);
    return status;
  }

  int Set(const std::string& key, const std::string& val) {
    return Request(OP_SET, key, val, nullptr);
  }

  int Get(const std::string& key, double timeout_s, int expected_reads,
          uint64_t nonce, std::string* out) {
    std::string arg(20, '\0');
    std::memcpy(&arg[0], &timeout_s, 8);
    int32_t er = expected_reads;
    std::memcpy(&arg[8], &er, 4);
    std::memcpy(&arg[12], &nonce, 8);
    return Request(OP_GET, key, arg, out);
  }

  int Del(const std::string& key) { return Request(OP_DEL, key, "", nullptr); }

  int Gather(const std::string& key, double timeout_s, int size, int rank,
             uint64_t nonce, const std::string& blob, std::string* out) {
    std::string arg(24, '\0');
    std::memcpy(&arg[0], &timeout_s, 8);
    int32_t s = size, r = rank;
    std::memcpy(&arg[8], &s, 4);
    std::memcpy(&arg[12], &r, 4);
    std::memcpy(&arg[16], &nonce, 8);
    arg += blob;
    return Request(OP_GATHER, key, arg, out);
  }

  int Reduce(const std::string& key, double timeout_s, int size, int rank,
             bool is_or, uint64_t nonce, const std::string& blob,
             std::string* out) {
    std::string arg(25, '\0');
    std::memcpy(&arg[0], &timeout_s, 8);
    int32_t s = size, r = rank;
    std::memcpy(&arg[8], &s, 4);
    std::memcpy(&arg[12], &r, 4);
    std::memcpy(&arg[16], &nonce, 8);
    arg[24] = is_or ? 1 : 0;
    arg += blob;
    return Request(OP_REDUCE, key, arg, out);
  }

  // Oversized-result stash: get/gather consume server-side read slots
  // BEFORE the reply, so "retry with a bigger buffer" would corrupt
  // round state — instead the wrapper stashes the full value here and
  // returns ST_AGAIN; the caller drains it with take_pending. The
  // request->ST_AGAIN->take_pending sequence must run under the SAME
  // external serialization as the request itself (one slot, not a
  // queue) — the Python StoreClient holds its per-client lock across
  // the pair.
  void StashPending(std::string v) {
    std::lock_guard<std::mutex> lk(mu_);
    pending_ = std::move(v);
  }
  std::string TakePending() {
    std::lock_guard<std::mutex> lk(mu_);
    return std::move(pending_);
  }

 private:
  // mu_ held (or ctor). Dial the server; leaves fd_ = -1 on failure.
  void Connect() {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) return;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (inet_pton(AF_INET, host_.c_str(), &addr.sin_addr) != 1) {
      // not a dotted quad — resolve via loopback fallback
      addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    }
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      ::close(fd_);
      fd_ = -1;
      return;
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  // mu_ held. Mark the transport broken: close the socket so state is
  // never half-trusted, and surface ST_CONN for the retry ladder.
  int Broken() {
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
    return ST_CONN;
  }

  std::string host_;
  int port_;
  int fd_ = -1;
  std::mutex mu_;
  std::string pending_;
};

// Coordinator: the reference controller's transport hook set
// (controller.h:49-157) implemented over the store. Each collective call
// consumes one sequence number; all ranks must call collectives in the same
// order (the same assumption the reference's negotiation protocol makes).
class Coordinator {
 public:
  Coordinator(const std::string& host, int port, int rank, int size)
      : client_(host, port), rank_(rank), size_(size) {
    // per-instance random salt for request nonces: stable for this
    // incarnation (retries of one logical collective reuse the nonce —
    // the server's replay dedupe key), distinct across relaunches so a
    // resurrected rank's fresh round is never mistaken for a replay
    std::random_device rd;
    inst_ = (static_cast<uint64_t>(rd()) << 32) ^ rd();
  }

  bool ok() const { return client_.ok(); }

  // Re-dial the underlying store connection after ST_CONN. Per-tag
  // sequence numbers are PRESERVED — that is the point of reconnecting
  // in place instead of rebuilding the coordinator: a replayed post
  // reuses the same key and nonce, so the server dedupes it.
  bool Reconnect() { return client_.Reconnect(); }

  std::string Key(const std::string& tag, uint64_t seq, int rank) {
    return "hvd/" + tag + "/" + std::to_string(seq) + "/" +
           std::to_string(rank);
  }

  // The request nonce for (tag, seq): deterministic for this instance,
  // so a transport retry of the same logical collective replays with
  // the same nonce; unique per round because seq advances on success.
  uint64_t NonceOf(const std::string& tag, uint64_t seq) {
    uint64_t h = std::hash<std::string>{}(tag);
    return (inst_ ^ (h * 0x9E3779B97F4A7C15ULL) ^ (seq + 1)) | 1;
  }

  // Per-tag sequence numbers, advanced only on SUCCESS: a retry of a
  // timed-out collective reuses the same key, so slow-peer retries stay
  // idempotent (the engine's negotiation retry loop depends on this).
  uint64_t SeqOf(const std::string& tag) {
    std::lock_guard<std::mutex> lk(seq_mu_);
    return tag_seq_[tag];
  }

  // Bound for tag_seq_: callers that bake a round/epoch into the tag
  // (one collective per tag, seq 0 -> 1, never touched again) would
  // otherwise grow the map for the job's lifetime. Far above the
  // steady-state tag population of every in-tree caller.
  static constexpr size_t kTagSeqCap = 4096;

  void Advance(const std::string& tag, uint64_t seq) {
    std::lock_guard<std::mutex> lk(seq_mu_);
    if (tag_seq_[tag] == seq) tag_seq_[tag] = seq + 1;
    if (tag_seq_.size() <= kTagSeqCap) return;
    // Prune advanced entries (seq > 0: their round completed; per-round
    // tags are single-use and will never be queried again). The prune
    // is DETERMINISTIC across ranks: every rank performs the identical
    // sequence of successful Advances (the same-call-order contract
    // above — retries don't advance), so all ranks drop the same
    // entries at the same logical point. A pruned long-lived tag
    // restarts at seq 0 on every rank simultaneously; its old rounds'
    // server state is already read-drained or TTL-swept, so the reused
    // keys cannot collide.
    for (auto it = tag_seq_.begin(); it != tag_seq_.end();) {
      if (it->first != tag && it->second > 0)
        it = tag_seq_.erase(it);
      else
        ++it;
    }
  }

  // Allgather of variable-size blobs. out = concat of u32-len-prefixed blobs
  // in rank order. ONE round trip via the server-side gather (OP_GATHER) —
  // the O(P)-sequential-Gets client loop capped negotiation at ~7 rounds/s
  // for 64 processes.
  int Allgather(const std::string& tag, const std::string& blob,
                double timeout_s, std::string* out) {
    uint64_t seq = SeqOf(tag);
    int st = client_.Gather(Key(tag, seq, -1), timeout_s, size_, rank_,
                            NonceOf(tag, seq), blob, out);
    if (st == ST_OK) Advance(tag, seq);
    return st;
  }

  int Barrier(const std::string& tag, double timeout_s) {
    // one-byte server-side reduce: same join semantics as the blob
    // allgather but with O(1) replies instead of the O(P) per-member
    // fan-out (store_service_time.py measures the difference)
    uint8_t bit = 1;
    return BitReduce(tag, &bit, 1, /*is_and=*/true, timeout_s);
  }

  int Bcast(const std::string& tag, int root, std::string* blob,
            double timeout_s) {
    uint64_t seq = SeqOf(tag);
    int st;
    if (rank_ == root) {
      if (size_ == 1) return ST_OK;
      // pass the status through untouched: ST_CONN must reach the
      // retry ladder as a connection fault, not a generic error
      st = client_.Set(Key(tag, seq, root), *blob);
    } else {
      // the read-counted Get carries the round nonce so a replay after
      // a lost reply is served again instead of double-decrementing
      // the fan-out count and starving a sibling reader
      st = client_.Get(Key(tag, seq, root), timeout_s, size_ - 1,
                       NonceOf(tag, seq), blob);
    }
    if (st == ST_OK) Advance(tag, seq);
    return st;
  }

  // In-place bitwise AND/OR allreduce of a bitvector — the cache-coordination
  // primitive (controller.cc:845 CoordinateCacheAndState). Server-side
  // reduce (OP_REDUCE): one round trip and an O(nbytes) reply per member
  // — the allgather-based variant's O(P*nbytes) reply fan-out was the
  // dominant control-plane cost at P=64
  // (benchmarks/store_service_time.py).
  int BitReduce(const std::string& tag, uint8_t* bits, uint32_t nbytes,
                bool is_and, double timeout_s) {
    std::string blob(reinterpret_cast<char*>(bits), nbytes);
    std::string acc;
    uint64_t seq = SeqOf(tag);
    int st = client_.Reduce(Key(tag, seq, -1), timeout_s, size_, rank_,
                            !is_and, NonceOf(tag, seq), blob, &acc);
    if (st != ST_OK) return st;
    if (acc.size() != nbytes) return ST_ERROR;
    std::memcpy(bits, acc.data(), nbytes);
    Advance(tag, seq);
    return ST_OK;
  }

  StoreClient client_;
  int rank_, size_;
  uint64_t inst_ = 0;
  std::mutex seq_mu_;
  std::map<std::string, uint64_t> tag_seq_;
};

}  // namespace

extern "C" {

void* hvd_store_server_create(int port) {
  auto* s = new StoreServer(port);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

int hvd_store_server_port(void* s) {
  return static_cast<StoreServer*>(s)->port();
}

void hvd_store_server_destroy(void* s) { delete static_cast<StoreServer*>(s); }

void* hvd_client_create(const char* host, int port) {
  auto* c = new StoreClient(host, port);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

void hvd_client_destroy(void* c) { delete static_cast<StoreClient*>(c); }

int hvd_client_set(void* c, const char* key, const uint8_t* val,
                   uint32_t len) {
  return static_cast<StoreClient*>(c)->Set(
      key, std::string(reinterpret_cast<const char*>(val), len));
}

// out must hold outcap bytes; sets *outlen to the full value size. When
// the value exceeds outcap the read was ALREADY consumed server-side
// (read-counted entries / gather slots), so re-requesting would corrupt
// state — the value is stashed client-side and ST_AGAIN returned; drain
// it with hvd_client_take_pending(outlen bytes).
int hvd_client_get(void* c, const char* key, double timeout_s,
                   int expected_reads, uint64_t nonce, uint8_t* out,
                   uint32_t outcap, uint32_t* outlen) {
  std::string v;
  int st = static_cast<StoreClient*>(c)->Get(key, timeout_s, expected_reads,
                                             nonce, &v);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(v.size());
  if (*outlen > outcap) {
    static_cast<StoreClient*>(c)->StashPending(std::move(v));
    return ST_AGAIN;
  }
  std::memcpy(out, v.data(), v.size());
  return ST_OK;
}

int hvd_client_take_pending(void* c, uint8_t* out, uint32_t outcap,
                            uint32_t* outlen) {
  std::string v = static_cast<StoreClient*>(c)->TakePending();
  *outlen = static_cast<uint32_t>(v.size());
  if (*outlen > outcap) {
    static_cast<StoreClient*>(c)->StashPending(std::move(v));
    return ST_AGAIN;
  }
  std::memcpy(out, v.data(), v.size());
  return ST_OK;
}

int hvd_client_del(void* c, const char* key) {
  return static_cast<StoreClient*>(c)->Del(key);
}

// Reconnect after ST_CONN; returns ST_OK / ST_CONN.
int hvd_client_reconnect(void* c) {
  return static_cast<StoreClient*>(c)->Reconnect() ? ST_OK : ST_CONN;
}

int hvd_client_gather(void* c, const char* key, double timeout_s, int size,
                      int rank, uint64_t nonce, const uint8_t* blob,
                      uint32_t bloblen, uint8_t* out, uint32_t outcap,
                      uint32_t* outlen) {
  std::string v;
  int st = static_cast<StoreClient*>(c)->Gather(
      key, timeout_s, size, rank, nonce,
      std::string(reinterpret_cast<const char*>(blob), bloblen), &v);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(v.size());
  if (*outlen > outcap) {
    static_cast<StoreClient*>(c)->StashPending(std::move(v));
    return ST_AGAIN;
  }
  std::memcpy(out, v.data(), v.size());
  return ST_OK;
}

int hvd_client_reduce(void* c, const char* key, double timeout_s, int size,
                      int rank, int is_or, uint64_t nonce,
                      const uint8_t* blob, uint32_t bloblen, uint8_t* out,
                      uint32_t outcap, uint32_t* outlen) {
  std::string v;
  int st = static_cast<StoreClient*>(c)->Reduce(
      key, timeout_s, size, rank, is_or != 0, nonce,
      std::string(reinterpret_cast<const char*>(blob), bloblen), &v);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(v.size());
  if (*outlen > outcap) {
    static_cast<StoreClient*>(c)->StashPending(std::move(v));
    return ST_AGAIN;
  }
  std::memcpy(out, v.data(), v.size());
  return ST_OK;
}

// "data=<n> gathers=<m> reduces=<k> svc_*=..." live-state counts after a
// forced TTL sweep — the leak-check hook (tests + doctor tooling).
int hvd_client_stat(void* c, uint8_t* out, uint32_t outcap,
                    uint32_t* outlen) {
  std::string v;
  int st = static_cast<StoreClient*>(c)->Request(OP_STAT, "", "", &v);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(v.size());
  if (*outlen > outcap) return ST_ERROR;
  std::memcpy(out, v.data(), v.size());
  return ST_OK;
}

void* hvd_coord_create(const char* host, int port, int rank, int size) {
  auto* co = new Coordinator(host, port, rank, size);
  if (!co->ok()) {
    delete co;
    return nullptr;
  }
  return co;
}

void hvd_coord_destroy(void* c) { delete static_cast<Coordinator*>(c); }

// Reconnect the coordinator's store connection after ST_CONN,
// preserving per-tag sequence state; returns ST_OK / ST_CONN.
int hvd_coord_reconnect(void* c) {
  return static_cast<Coordinator*>(c)->Reconnect() ? ST_OK : ST_CONN;
}

int hvd_coord_barrier(void* c, const char* tag, double timeout_s) {
  return static_cast<Coordinator*>(c)->Barrier(tag, timeout_s);
}

int hvd_coord_allgather(void* c, const char* tag, const uint8_t* val,
                        uint32_t len, double timeout_s, uint8_t* out,
                        uint32_t outcap, uint32_t* outlen) {
  std::string o;
  int st = static_cast<Coordinator*>(c)->Allgather(
      tag, std::string(reinterpret_cast<const char*>(val), len), timeout_s,
      &o);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(o.size());
  if (*outlen > outcap) return ST_ERROR;
  std::memcpy(out, o.data(), o.size());
  return ST_OK;
}

int hvd_coord_bcast(void* c, const char* tag, int root, const uint8_t* val,
                    uint32_t len, double timeout_s, uint8_t* out,
                    uint32_t outcap, uint32_t* outlen) {
  std::string blob(reinterpret_cast<const char*>(val), len);
  int st = static_cast<Coordinator*>(c)->Bcast(tag, root, &blob, timeout_s);
  if (st != ST_OK) return st;
  *outlen = static_cast<uint32_t>(blob.size());
  if (*outlen > outcap) return ST_ERROR;
  std::memcpy(out, blob.data(), blob.size());
  return ST_OK;
}

int hvd_coord_bitand(void* c, const char* tag, uint8_t* bits, uint32_t nbytes,
                     double timeout_s) {
  return static_cast<Coordinator*>(c)->BitReduce(tag, bits, nbytes, true,
                                                 timeout_s);
}

int hvd_coord_bitor(void* c, const char* tag, uint8_t* bits, uint32_t nbytes,
                    double timeout_s) {
  return static_cast<Coordinator*>(c)->BitReduce(tag, bits, nbytes, false,
                                                 timeout_s);
}

}  // extern "C"
