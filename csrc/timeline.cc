// Native Chrome-trace timeline writer.
//
// TPU-native re-design of the reference Timeline (horovod/common/timeline.cc:
// a writer thread fed by a boost lockfree SPSC queue, timeline.h:48-70).
// Emitting threads format one compact JSON event and hand it to a
// mutex+condvar MPSC queue; a dedicated writer thread batches buffered
// appends. The file is a streaming Chrome trace: "{"traceEvents":[" then
// comma-separated events; destroy() seals it with "]}" so the finished file
// is valid JSON (the reference leaves the array unterminated —
// timeline.cc WriteAtFileStart).
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace {

void append_escaped(std::string* out, const char* s) {
  for (; *s; ++s) {
    unsigned char c = static_cast<unsigned char>(*s);
    switch (c) {
      case '"':
        *out += "\\\"";
        break;
      case '\\':
        *out += "\\\\";
        break;
      case '\n':
        *out += "\\n";
        break;
      case '\t':
        *out += "\\t";
        break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
}

class TimelineWriter {
 public:
  explicit TimelineWriter(const std::string& path) {
    file_ = std::fopen(path.c_str(), "w");
    if (!file_) return;
    std::fputs("{\"traceEvents\":[", file_);
    thread_ = std::thread([this] { Loop(); });
  }

  ~TimelineWriter() {
    if (!file_) return;
    {
      std::lock_guard<std::mutex> lk(mu_);
      stopping_ = true;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
    std::fputs("]}", file_);
    std::fclose(file_);
  }

  bool ok() const { return file_ != nullptr; }

  void Emit(const char* name, const char* cat, char ph, int64_t ts_us,
            int pid, int64_t tid, const char* args_json) {
    if (!file_) return;
    std::string ev;
    ev.reserve(96);
    ev += "{\"name\":\"";
    append_escaped(&ev, name);
    ev += "\",\"ph\":\"";
    ev.push_back(ph);
    ev += "\"";
    if (cat && *cat) {
      ev += ",\"cat\":\"";
      append_escaped(&ev, cat);
      ev += "\"";
    }
    if (ph == 'i') ev += ",\"s\":\"g\"";
    ev += ",\"ts\":" + std::to_string(ts_us);
    ev += ",\"pid\":" + std::to_string(pid);
    ev += ",\"tid\":" + std::to_string(tid);
    if (args_json && *args_json) {
      ev += ",\"args\":";
      ev += args_json;  // caller-provided JSON object
    }
    ev += "}";
    {
      std::lock_guard<std::mutex> lk(mu_);
      queue_.push_back(std::move(ev));
    }
    cv_.notify_one();
  }

 private:
  void Loop() {
    std::deque<std::string> batch;
    bool first = true;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stopping_ || !queue_.empty(); });
        batch.swap(queue_);
        if (batch.empty() && stopping_) break;
      }
      for (auto& ev : batch) {
        if (!first) std::fputc(',', file_);
        first = false;
        std::fwrite(ev.data(), 1, ev.size(), file_);
      }
      batch.clear();
      std::fflush(file_);
    }
  }

  std::FILE* file_ = nullptr;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  bool stopping_ = false;
};

}  // namespace

extern "C" {

void* hvd_timeline_create(const char* path) {
  auto* t = new TimelineWriter(path);
  if (!t->ok()) {
    delete t;
    return nullptr;
  }
  return t;
}

void hvd_timeline_destroy(void* t) { delete static_cast<TimelineWriter*>(t); }

void hvd_timeline_emit(void* t, const char* name, const char* cat, char ph,
                       int64_t ts_us, int pid, int64_t tid,
                       const char* args_json) {
  static_cast<TimelineWriter*>(t)->Emit(name, cat, ph, ts_us, pid, tid,
                                        args_json);
}

}  // extern "C"
